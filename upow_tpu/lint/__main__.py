"""CLI entry point: ``python -m upow_tpu.lint [paths ...]``."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .engine import run_lint
from .rules import ALL_RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.lint",
        description="upowlint: consensus-safety & JAX-purity static analysis")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the upow_tpu package)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json includes suppressed findings)")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (e.g. CE001,JP001)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.rule_id}  [{rule.severity:7s}]  {rule.description}")
        return 0

    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
    result = run_lint(paths, select=select)
    print(result.to_json() if args.format == "json" else result.to_text())
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
