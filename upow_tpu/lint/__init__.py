"""upowlint — AST-based consensus-safety and JAX-purity checks.

Run as ``python -m upow_tpu.lint [paths] [--format json]``; exits 1 when
any error-severity finding survives suppression.  See
docs/STATIC_ANALYSIS.md for the rule catalogue and the reasoning behind
each family.

This subpackage must stay importable without jax installed — CI's lint
job and pre-commit hooks run it in bare environments.
"""

from .engine import (Finding, LintResult, SEVERITY_ERROR, SEVERITY_WARNING,
                     run_lint)

__all__ = ["Finding", "LintResult", "SEVERITY_ERROR", "SEVERITY_WARNING",
           "run_lint"]
