"""upowlint engine: file discovery, suppression parsing, rule running.

Deliberately dependency-free (stdlib ``ast`` only) and independent of the
rest of the package — ``python -m upow_tpu.lint`` must start fast and run
in environments without jax (CI's lint job, pre-commit hooks).

Rule protocol
-------------
A rule is an object with:

* ``rule_id``     — short code, e.g. ``"CE001"`` (family prefix + number).
* ``severity``    — ``"error"`` or ``"warning"``; only errors gate exit 0.
* ``description`` — one line, shown by ``--list-rules``.
* ``scope(parts)``— predicate over the file's path parts (package-relative
  when inside ``upow_tpu/``); limits domain rules to the layers where the
  invariant they police actually holds (e.g. consensus purity only inside
  ``core``/``crypto``/``verify``).
* ``check(ctx)``  — yields ``(line, col, message)`` tuples (the engine
  attaches path/rule/severity and applies suppressions).

Suppression
-----------
``# upowlint: disable=CE001`` (comma-separated list, or ``all``) on the
line a finding is reported at suppresses it.  Every suppression in the
tree is expected to carry a justification in the same comment or the line
above — that convention is reviewed, not machine-enforced.
"""

from __future__ import annotations

import ast
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_SUPPRESS_RE = re.compile(r"#\s*upowlint:\s*disable=([A-Za-z0-9_*,\s]+)")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str

    def as_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "col": self.col,
            "rule": self.rule, "severity": self.severity,
            "message": self.message,
        }


@dataclass
class FileContext:
    """Everything a rule gets to look at for one file."""

    path: Path                 # as discovered
    rel: str                   # posix path relative to the lint root
    parts: Tuple[str, ...]     # rel split into components
    tree: ast.Module
    source: str
    lines: List[str] = field(default_factory=list)


@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    files_scanned: int

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_WARNING]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_json(self) -> str:
        return json.dumps({
            "files_scanned": self.files_scanned,
            "counts": {
                "error": len(self.errors),
                "warning": len(self.warnings),
                "suppressed": len(self.suppressed),
            },
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
        }, indent=2)

    def to_text(self) -> str:
        out = [
            f"{f.path}:{f.line}:{f.col}: {f.rule} [{f.severity}] {f.message}"
            for f in self.findings
        ]
        out.append(
            f"upowlint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{self.files_scanned} file(s) scanned")
        return "\n".join(out)


def _package_root() -> Path:
    """Directory that CONTAINS the upow_tpu package (repo root in-tree)."""
    return Path(__file__).resolve().parent.parent.parent


def relative_parts(path: Path) -> Tuple[str, Tuple[str, ...]]:
    """Path components used for rule scoping.

    Files inside the ``upow_tpu`` package are keyed package-relative
    (``core/tx.py``); anything else (test fixtures, scripts) falls back to
    the path relative to the cwd, or its absolute components.  Scoping is
    by directory NAME (``"core" in parts``), so fixture trees like
    ``tests/lint_fixtures/core/x.py`` land in the same scope as the real
    module — that is what lets the test suite exercise scoped rules.
    """
    resolved = path.resolve()
    for anchor in (_package_root() / "upow_tpu", Path.cwd()):
        try:
            rel = resolved.relative_to(anchor.resolve())
            return rel.as_posix(), rel.parts
        except ValueError:
            continue
    return resolved.as_posix(), resolved.parts


def discover(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    # dedupe preserving order
    seen: Set[Path] = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen and "__pycache__" not in f.parts:
            seen.add(r)
            out.append(f)
    return out


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """line -> set of rule ids disabled on that line ('*' disables all).

    Tokenize-based so a ``# upowlint:`` inside a string literal is not
    honored; falls back to a line scan if tokenization fails.
    """
    out: Dict[int, Set[str]] = {}

    def record(lineno: int, spec: str) -> None:
        rules = {r.strip() for r in spec.split(",") if r.strip()}
        if "all" in rules:
            rules = {"*"}
        out.setdefault(lineno, set()).update(rules)

    try:
        import io

        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    record(tok.start[0], m.group(1))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                record(i, m.group(1))
    return out


def run_lint(paths: Sequence[str], rules: Optional[Sequence] = None,
             select: Optional[Set[str]] = None) -> LintResult:
    """Run ``rules`` (default: the full registry) over ``paths``."""
    if rules is None:
        from .rules import ALL_RULES

        rules = ALL_RULES
    if select:
        rules = [r for r in rules if r.rule_id in select]
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    files = discover(paths)
    for path in files:
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(path))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                str(path), getattr(e, "lineno", 1) or 1, 0, "LINT000",
                SEVERITY_ERROR, f"file does not parse: {e.msg if hasattr(e, 'msg') else e}"))
            continue
        rel, parts = relative_parts(path)
        ctx = FileContext(path=path, rel=rel, parts=parts, tree=tree,
                          source=source, lines=source.splitlines())
        per_line = parse_suppressions(source)
        for rule in rules:
            if not rule.scope(parts):
                continue
            for line, col, message in rule.check(ctx):
                f = Finding(str(path), line, col, rule.rule_id,
                            rule.severity, message)
                disabled = per_line.get(line, set())
                if "*" in disabled or rule.rule_id in disabled:
                    suppressed.append(f)
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings=findings, suppressed=suppressed,
                      files_scanned=len(files))


# --- shared AST helpers used by several rule modules ----------------------

def dotted_name(node: ast.AST) -> str:
    """'time.time' for Attribute/Name chains, '' when not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_function_defs(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
