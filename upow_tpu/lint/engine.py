"""upowlint engine: file discovery, suppression parsing, rule running.

Deliberately dependency-free (stdlib ``ast`` only) and independent of the
rest of the package — ``python -m upow_tpu.lint`` must start fast and run
in environments without jax (CI's lint job, pre-commit hooks).

Rule protocol
-------------
A rule is an object with:

* ``rule_id``     — short code, e.g. ``"CE001"`` (family prefix + number).
* ``severity``    — ``"error"`` or ``"warning"``; only errors gate exit 0.
* ``description`` — one line, shown by ``--list-rules``.
* ``scope(parts)``— predicate over the file's path parts (package-relative
  when inside ``upow_tpu/``); limits domain rules to the layers where the
  invariant they police actually holds (e.g. consensus purity only inside
  ``core``/``crypto``/``verify``).
* ``check(ctx)``  — yields ``(line, col, message)`` tuples (the engine
  attaches path/rule/severity and applies suppressions).

Project-scope rules (the RC family) additionally set
``requires_project = True`` and implement ``check_project(project)``,
yielding ``(rel, line, col, message)`` tuples over the whole linted set;
the engine builds one :class:`upow_tpu.lint.project.ProjectContext`
(symbol table + call graph + loop/thread coloring) per run — lazily, only
when a selected rule asks for it — and applies each file's scope and
suppressions to the findings exactly as for file rules.  Every file rule
sees the same context at ``ctx.project`` (``None`` unless built).

``--select`` accepts exact ids (``DR002``) and family prefixes (``RC``).

Baseline mode
-------------
``run_lint(..., baseline=...)`` takes a mapping of finding fingerprints
(see :func:`fingerprint`) to allowed counts; matching findings move to
``result.baselined`` and stop gating the exit code, so a new rule family
can land before the tree is swept.  Fingerprints hash the lint-root
relative path, rule id, and the stripped source line text — stable across
reordering, invalidated when the flagged line actually changes.

Suppression
-----------
``# upowlint: disable=CE001`` (comma-separated list, or ``all``) on the
line a finding is reported at suppresses it.  Every suppression in the
tree is expected to carry a justification in the same comment or the line
above — that convention is reviewed, not machine-enforced.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Set, \
    Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_SUPPRESS_RE = re.compile(r"#\s*upowlint:\s*disable=([A-Za-z0-9_*,\s]+)")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str

    def as_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "col": self.col,
            "rule": self.rule, "severity": self.severity,
            "message": self.message,
        }


@dataclass
class FileContext:
    """Everything a rule gets to look at for one file."""

    path: Path                 # as discovered
    rel: str                   # posix path relative to the lint root
    parts: Tuple[str, ...]     # rel split into components
    tree: ast.Module
    source: str
    lines: List[str] = field(default_factory=list)
    project: Optional[object] = None   # ProjectContext when built


@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    files_scanned: int
    baselined: List[Finding] = field(default_factory=list)
    fingerprint_counts: Dict[str, int] = field(default_factory=dict)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_WARNING]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_json(self) -> str:
        return json.dumps({
            "files_scanned": self.files_scanned,
            "counts": {
                "error": len(self.errors),
                "warning": len(self.warnings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "baselined": [f.as_dict() for f in self.baselined],
        }, indent=2)

    def to_text(self) -> str:
        out = [
            f"{f.path}:{f.line}:{f.col}: {f.rule} [{f.severity}] {f.message}"
            for f in self.findings
        ]
        out.append(
            f"upowlint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined, "
            f"{self.files_scanned} file(s) scanned")
        return "\n".join(out)


def _package_root() -> Path:
    """Directory that CONTAINS the upow_tpu package (repo root in-tree)."""
    return Path(__file__).resolve().parent.parent.parent


def relative_parts(path: Path) -> Tuple[str, Tuple[str, ...]]:
    """Path components used for rule scoping.

    Files inside the ``upow_tpu`` package are keyed package-relative
    (``core/tx.py``); anything else (test fixtures, scripts) falls back to
    the path relative to the cwd, or its absolute components.  Scoping is
    by directory NAME (``"core" in parts``), so fixture trees like
    ``tests/lint_fixtures/core/x.py`` land in the same scope as the real
    module — that is what lets the test suite exercise scoped rules.
    """
    resolved = path.resolve()
    for anchor in (_package_root() / "upow_tpu", Path.cwd()):
        try:
            rel = resolved.relative_to(anchor.resolve())
            return rel.as_posix(), rel.parts
        except ValueError:
            continue
    return resolved.as_posix(), resolved.parts


def discover(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    # dedupe preserving order
    seen: Set[Path] = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen and "__pycache__" not in f.parts:
            seen.add(r)
            out.append(f)
    return out


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """line -> set of rule ids disabled on that line ('*' disables all).

    Tokenize-based so a ``# upowlint:`` inside a string literal is not
    honored; falls back to a line scan if tokenization fails.
    """
    out: Dict[int, Set[str]] = {}

    def record(lineno: int, spec: str) -> None:
        rules = {r.strip() for r in spec.split(",") if r.strip()}
        if "all" in rules:
            rules = {"*"}
        out.setdefault(lineno, set()).update(rules)

    try:
        import io

        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    record(tok.start[0], m.group(1))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                record(i, m.group(1))
    return out


def _rule_selected(rule_id: str, select: Set[str]) -> bool:
    """Exact id (``DR002``) or family-prefix (``RC``) match."""
    return any(rule_id == s or rule_id.startswith(s) for s in select)


def fingerprint(rel: str, rule: str, line_text: str) -> str:
    """Stable identity of a finding for baseline mode: lint-root
    relative path + rule id + the stripped source line.  Survives the
    file moving up or down; breaks (on purpose) when the flagged line
    itself is edited."""
    raw = f"{rel}|{rule}|{line_text.strip()}"
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]


def run_lint(paths: Sequence[str], rules: Optional[Sequence] = None,
             select: Optional[Set[str]] = None,
             baseline: Optional[Mapping[str, int]] = None) -> LintResult:
    """Run ``rules`` (default: the full registry) over ``paths``."""
    if rules is None:
        from .rules import ALL_RULES

        rules = ALL_RULES
    if select:
        rules = [r for r in rules if _rule_selected(r.rule_id, select)]
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    files = discover(paths)

    # Pass 1: parse everything (project rules need the full set).
    contexts: List[FileContext] = []
    for path in files:
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(path))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                str(path), getattr(e, "lineno", 1) or 1, 0, "LINT000",
                SEVERITY_ERROR, f"file does not parse: {e.msg if hasattr(e, 'msg') else e}"))
            continue
        rel, parts = relative_parts(path)
        contexts.append(FileContext(
            path=path, rel=rel, parts=parts, tree=tree, source=source,
            lines=source.splitlines()))

    project = None
    project_rules = [r for r in rules
                     if getattr(r, "requires_project", False)]
    if project_rules:
        from .project import ProjectContext

        project = ProjectContext.build(contexts)

    by_rel: Dict[str, FileContext] = {}
    suppressions: Dict[str, Dict[int, Set[str]]] = {}
    for ctx in contexts:
        ctx.project = project
        by_rel[ctx.rel] = ctx
        suppressions[ctx.rel] = parse_suppressions(ctx.source)

    def emit(ctx: FileContext, rule, line: int, col: int,
             message: str) -> None:
        f = Finding(str(ctx.path), line, col, rule.rule_id,
                    rule.severity, message)
        disabled = suppressions[ctx.rel].get(line, set())
        if "*" in disabled or rule.rule_id in disabled:
            suppressed.append(f)
        else:
            findings.append(f)

    # Pass 2: file rules.
    for ctx in contexts:
        for rule in rules:
            if not rule.scope(ctx.parts):
                continue
            for line, col, message in rule.check(ctx):
                emit(ctx, rule, line, col, message)

    # Pass 3: project rules (one traversal each, findings routed back
    # through the owning file's scope + suppressions).
    for rule in project_rules:
        for rel, line, col, message in rule.check_project(project):
            ctx = by_rel.get(rel)
            if ctx is None or not rule.scope(ctx.parts):
                continue
            emit(ctx, rule, line, col, message)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    # Fingerprints (always computed: --write-baseline reads them).
    rel_by_path = {str(c.path): c.rel for c in contexts}
    lines_by_path = {str(c.path): c.lines for c in contexts}
    fp_counts: Dict[str, int] = {}
    fps: List[str] = []
    for f in findings:
        lines = lines_by_path.get(f.path, [])
        text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        fp = fingerprint(rel_by_path.get(f.path, f.path), f.rule, text)
        fps.append(fp)
        fp_counts[fp] = fp_counts.get(fp, 0) + 1

    baselined: List[Finding] = []
    if baseline:
        used: Dict[str, int] = {}
        kept: List[Finding] = []
        for f, fp in zip(findings, fps):
            if used.get(fp, 0) < int(baseline.get(fp, 0)):
                used[fp] = used.get(fp, 0) + 1
                baselined.append(f)
            else:
                kept.append(f)
        findings = kept

    return LintResult(findings=findings, suppressed=suppressed,
                      files_scanned=len(files), baselined=baselined,
                      fingerprint_counts=fp_counts)


# --- shared AST helpers used by several rule modules ----------------------

def dotted_name(node: ast.AST) -> str:
    """'time.time' for Attribute/Name chains, '' when not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_function_defs(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
