"""DT — dtype-hygiene for device limb arithmetic (``crypto/``, ``mine/``).

The 256-bit field arithmetic (``crypto/fp.py``) lives entirely in 13-bit
limbs inside **int32** lanes — the whole design is a proof that no
intermediate exceeds 2^31 (see fp.py's sweep-count proofs).  The two ways
that proof silently dies:

* a 64-bit dtype sneaks in: without ``jax_enable_x64`` JAX silently
  *downcasts* int64 to int32 (values truncate, no error), and with it the
  TPU VPU has no native 64-bit integer path (everything slows down);
* a binop mixes explicit dtypes or wraps an out-of-range Python int,
  promoting lanes or wrapping at construction time.

* DT001 — any reference to ``int64`` / ``uint64`` / ``float64`` via
  np/jnp (call, ``dtype=`` kw, or ``astype`` argument).  Host-side exact
  conversions are legitimate — justify + suppress those.
* DT002 — binop whose two operands are explicit dtype constructors of
  DIFFERENT dtypes (``jnp.uint32(a) + jnp.int32(b)``): promotion makes
  the result dtype depend on jax's promotion lattice, not the author.
* DT003 — explicit 32-bit dtype constructor wrapping an integer literal
  that does not fit (``jnp.uint32(2**40)``, ``jnp.int32(2**31)``,
  ``jnp.uint32(-1)``): wraps silently at trace time.
"""

from __future__ import annotations

import ast
from typing import Optional, Tuple

from ..engine import SEVERITY_ERROR, FileContext, dotted_name

_SCOPE = {"crypto", "mine"}
_WIDE = {"int64", "uint64", "float64"}
_NARROW_RANGES = {
    "int32": (-(2 ** 31), 2 ** 31 - 1),
    "uint32": (0, 2 ** 32 - 1),
    "int16": (-(2 ** 15), 2 ** 15 - 1),
    "uint16": (0, 2 ** 16 - 1),
    "int8": (-(2 ** 7), 2 ** 7 - 1),
    "uint8": (0, 2 ** 8 - 1),
}
_NS = {"np", "jnp", "numpy"}


def _dtype_of(node: ast.AST) -> Optional[str]:
    """'uint32' for ``jnp.uint32`` / ``np.uint32`` attribute chains."""
    name = dotted_name(node)
    if "." in name:
        ns, attr = name.rsplit(".", 1)
        if ns in _NS:
            return attr
    return None


class _DtypeRule:
    severity = SEVERITY_ERROR
    requires_project = False    # per-file lexical rules (project API opt-out)

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return bool(_SCOPE.intersection(parts[:-1]))


class WideDtypeRule(_DtypeRule):
    rule_id = "DT001"
    description = "64-bit dtype in device limb-arithmetic scope"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                dtype = _dtype_of(node)
                if dtype in _WIDE:
                    yield (node.lineno, node.col_offset,
                           f"{dotted_name(node)} in device-arithmetic scope"
                           " — JAX silently downcasts to 32-bit without "
                           "jax_enable_x64 and the TPU has no native "
                           "64-bit integer lanes; keep limb math in int32 "
                           "(justify+suppress for host-only conversions)")


class MixedDtypeBinopRule(_DtypeRule):
    rule_id = "DT002"
    description = "binop mixing two explicit, different dtype constructors"

    @staticmethod
    def _ctor_dtype(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            return _dtype_of(node.func)
        return None

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.BinOp):
                lt = self._ctor_dtype(node.left)
                rt = self._ctor_dtype(node.right)
                if lt and rt and lt != rt:
                    yield (node.lineno, node.col_offset,
                           f"binop mixes explicit dtypes {lt} and {rt} — "
                           "the result dtype follows jax's promotion "
                           "lattice, not the wider operand; cast one side "
                           "explicitly")


class OverflowLiteralRule(_DtypeRule):
    rule_id = "DT003"
    description = "integer literal out of range for its explicit narrow dtype"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and len(node.args) == 1):
                continue
            dtype = _dtype_of(node.func)
            if dtype not in _NARROW_RANGES:
                continue
            value = _const_int(node.args[0])
            if value is None:
                continue
            lo, hi = _NARROW_RANGES[dtype]
            if not (lo <= value <= hi):
                yield (node.lineno, node.col_offset,
                       f"{value} does not fit in {dtype} "
                       f"[{lo}, {hi}] — wraps silently at trace time")


def _const_int(node: ast.AST) -> Optional[int]:
    """Evaluate small constant int expressions (literals, 2**40, -1, 1<<35)."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.BinOp):
        left, right = _const_int(node.left), _const_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Pow):
                return left ** right if abs(right) < 512 else None
            if isinstance(node.op, ast.LShift):
                return left << right if right < 512 else None
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
        except (OverflowError, ValueError):
            return None
    return None


RULES = [WideDtypeRule(), MixedDtypeBinopRule(), OverflowLiteralRule()]
