"""BE — broad-except hygiene (whole package).

``except Exception`` that neither re-raises nor records anything turns
real failures — a corrupt DB row, a poisoned device, a peer speaking
garbage — into silence.  The node keeps "working" while its mempool
drains or its sync quietly stops advancing.  Broad catches are often
*correct* at daemon boundaries (a background loop must not die), but they
must leave a trace.

BE001 flags an ``except Exception`` / ``except BaseException`` / bare
``except:`` handler whose body contains neither:

* a ``raise`` (re-raise or translate), nor
* a logging-ish call — any ``.debug/.info/.warning/.error/.exception/
  .critical/.log`` method call, or ``print`` (the CLI's reporting
  channel), nor
* an assignment that *captures* the caught exception object for the
  caller (``box["err"] = e`` — the thread-boxing pattern).
"""

from __future__ import annotations

import ast
from typing import Tuple

from ..engine import SEVERITY_ERROR, FileContext

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts)
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    caught = handler.name  # 'e' in `except Exception as e`
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _LOG_METHODS:
                return True
            if isinstance(func, ast.Name) and func.id == "print":
                return True
        if caught and isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and node.value.id == caught:
            return True  # exception object handed to someone else
    return False


class BroadExceptRule:
    rule_id = "BE001"
    severity = SEVERITY_ERROR
    requires_project = False    # per-file lexical rule (project API opt-out)
    description = "except Exception without re-raise, log call, or capture"

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return True

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and not _handles(node):
                yield (node.lineno, node.col_offset,
                       "broad except swallows failures silently — narrow "
                       "the exception type, re-raise, or add a "
                       "log.exception(...)/log.debug(...) call")


RULES = [BroadExceptRule()]
