"""CE — consensus-endianness.

The uPow wire format is little-endian end to end (``core/constants.py:
ENDIAN = "little"``, mirroring the reference's ``constants.py:3``).  A
big-endian ``to_bytes``/``from_bytes`` in a serialization module is a
consensus break that no unit test exercising only our own encoder+decoder
can catch (both sides agree with each other and disagree with the chain).
A *bare* call is just as dangerous: Python 3.11 made ``byteorder``
default to ``"big"``, so code that "works" on 3.10 by raising starts
silently producing big-endian bytes on 3.11+.

Allowlist: algorithms whose own specification fixes big-endian byte order
are exempt as whole modules —

* ``crypto/sha256.py`` — SHA-256's message schedule, length field and
  digest words are big-endian by FIPS 180-4 (e.g. the padding length at
  ``sha256.py:92``).
* ``crypto/p256.py``   — ECDSA's bits2int / digest-to-scalar conversion
  is big-endian per SEC 1 / RFC 6979 (e.g. ``p256.py:1344``).
* ``core/curve.py``    — the deterministic-nonce RFC 6979 helpers
  (bits2int/int2octets) share that convention.

Anything else big-endian in consensus scope must carry an inline
``# upowlint: disable=CE001`` with a justification (e.g. base58's bigint
convention in ``core/codecs.py``).
"""

from __future__ import annotations

import ast
from typing import Iterable, Tuple

from ..engine import SEVERITY_ERROR, FileContext

_SCOPE = {"core", "crypto", "verify"}
ALLOWLIST = ("crypto/sha256.py", "crypto/p256.py", "core/curve.py")


def _in_allowlist(parts: Tuple[str, ...]) -> bool:
    joined = "/".join(parts)
    return any(joined.endswith(entry) for entry in ALLOWLIST)


class _EndiannessRule:
    severity = SEVERITY_ERROR
    requires_project = False    # per-file lexical rules (project API opt-out)

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return bool(_SCOPE.intersection(parts[:-1])) and not _in_allowlist(parts)

    @staticmethod
    def _byteorder_arg(call: ast.Call):
        """The byteorder expression of a to_bytes/from_bytes call, or None.

        Both signatures put byteorder second: ``int.to_bytes(length,
        byteorder)`` / ``int.from_bytes(bytes, byteorder)``.
        """
        if len(call.args) >= 2:
            return call.args[1]
        for kw in call.keywords:
            if kw.arg == "byteorder":
                return kw.value
        return None

    def _calls(self, ctx: FileContext) -> Iterable[ast.Call]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("to_bytes", "from_bytes"):
                yield node


class BigEndianRule(_EndiannessRule):
    rule_id = "CE001"
    description = ("explicit 'big' byteorder in consensus serialization "
                   "(uPow wire format is little-endian)")

    def check(self, ctx: FileContext):
        for call in self._calls(ctx):
            order = self._byteorder_arg(call)
            if isinstance(order, ast.Constant) and order.value == "big":
                yield (call.lineno, call.col_offset,
                       "big-endian to_bytes/from_bytes in consensus scope; "
                       "the uPow wire format is little-endian — use "
                       "core.constants.ENDIAN (or justify+suppress for "
                       "algorithm-mandated byte order)")


class BareByteorderRule(_EndiannessRule):
    rule_id = "CE002"
    description = ("to_bytes/from_bytes without an explicit byteorder "
                   "(defaults to big-endian on Python 3.11+)")

    def check(self, ctx: FileContext):
        for call in self._calls(ctx):
            if self._byteorder_arg(call) is None:
                yield (call.lineno, call.col_offset,
                       "bare to_bytes/from_bytes: byteorder defaults to "
                       "'big' on Python 3.11+ (and raises on 3.10) — pass "
                       "core.constants.ENDIAN explicitly")


RULES = [BigEndianRule(), BareByteorderRule()]
