"""CP — consensus-purity.

The consensus layers (``core/``, ``crypto/``, ``verify/``) must be
bit-exact with the reference chain: integer / Decimal arithmetic only,
one injectable clock, and no iteration order that can differ between two
processes validating the same block.

* CP001 — float literal.  IEEE doubles round: ``Decimal(0.5)`` happens to
  be exact but ``Decimal(0.1)`` is not, and ``x / 10.0`` can disagree
  with the reference's Decimal math by one ulp — enough to fork.
* CP002 — direct wall-clock read (``time.time``, ``datetime.now``, ...).
  Every consensus-path timestamp must come from ``core/clock.timestamp``
  so tests (and reorg tooling) can move the whole node through time
  together.  ``time.monotonic``/``perf_counter`` are NOT flagged: they
  are not wall-clock and are legitimate for caches and profiling.
* CP003 — iteration over a set.  Set order depends on string hash
  randomization (PYTHONHASHSEED), so two nodes iterating the same set
  can serialize/apply in different orders.  Dicts are not flagged:
  Python dicts iterate in insertion order, which is deterministic.
* CP004 — ``float(...)`` conversion.  Same ulp hazard as CP001 but at
  runtime on chain data (the classic is ``int(float(difficulty) * 10)``).

``core/clock.py`` itself is exempt — it is the one designated wrapper
around ``time.time``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Tuple

from ..engine import SEVERITY_ERROR, FileContext, dotted_name

_SCOPE = {"core", "crypto", "verify"}

_WALL_CLOCK = {
    "time.time", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today", "date.today",
}


class _ConsensusRule:
    severity = SEVERITY_ERROR
    requires_project = False    # per-file lexical rules (project API opt-out)

    def scope(self, parts: Tuple[str, ...]) -> bool:
        if parts[-1:] == ("clock.py",) and "core" in parts:
            return False
        return bool(_SCOPE.intersection(parts[:-1]))


class FloatLiteralRule(_ConsensusRule):
    rule_id = "CP001"
    description = "float literal in consensus scope"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and type(node.value) is float:
                yield (node.lineno, node.col_offset,
                       f"float literal {node.value!r} in consensus scope — "
                       "use int smallest-units or Decimal('...') (or "
                       "justify+suppress for non-consensus operational "
                       "values such as timeouts)")


class WallClockRule(_ConsensusRule):
    rule_id = "CP002"
    description = "direct wall-clock read in consensus scope"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in _WALL_CLOCK:
                    yield (node.lineno, node.col_offset,
                           f"{name}() in consensus scope — route through "
                           "core.clock.timestamp() so the whole node moves "
                           "through time together")


class SetIterationRule(_ConsensusRule):
    rule_id = "CP003"
    description = "iteration over a set in consensus scope"

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("set", "frozenset"):
            return True
        return False

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            iters = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(g.iter for g in node.generators)
            for it in iters:
                if self._is_set_expr(it):
                    yield (it.lineno, it.col_offset,
                           "iterating a set in consensus scope — order "
                           "depends on hash randomization; sort first "
                           "(sorted(...)) or use a list/dict")


class FloatConversionRule(_ConsensusRule):
    rule_id = "CP004"
    description = "float() conversion in consensus scope"

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "float":
                yield (node.lineno, node.col_offset,
                       "float() on consensus data loses exactness — keep "
                       "Decimal/int end to end (classic fork: "
                       "int(float(difficulty) * 10))")


RULES = [FloatLiteralRule(), WallClockRule(), SetIterationRule(),
         FloatConversionRule()]
