"""DR — device-runtime purity.

ISSUE 10 routes every device dispatch through ONE owner:
``upow_tpu/device/runtime.py``.  The runtime arms the backend exactly
once under a deadline, coalesces compatible submissions across
subsystems, schedules them with weighted fairness, and gives the
degrade controller a single choke point.  All of that is void the
moment some subsystem talks to the chip directly — a stray
``jax.devices()`` can *initialize the backend* (hanging the process on
a dead tunnel with no deadline), and a stray ``boxed_call`` dispatch
races the fair scheduler for the chip.

Rules (all errors, scoped to everything OUTSIDE ``device/`` and
``lint/``):

* DR001 — backend init/enumeration outside ``device/``:
  ``jax.devices`` / ``jax.local_devices`` / ``jax.device_count`` /
  ``jax.local_device_count`` / ``jax.default_backend`` /
  ``jax.device_put`` / ``jax.device_get``.  Use
  ``get_runtime().devices()`` / ``.platform()`` instead — they wait on
  the armed (deadline-bounded) backend.
* DR002 — ``boxed_call(...)`` outside ``device/``: the thread-boxed
  dispatch shim is the runtime's internal primitive now; subsystems
  submit via ``get_runtime().run_boxed`` / ``submit_call`` /
  ``submit_sig_checks`` so their work lands in the fair queues.
* DR003 — ``jax.jit`` / ``pjit`` called as an *expression inside a
  function body* outside ``device/``: staging a dispatchable at call
  time bypasses arm-time AOT warming and hides a dispatch site from
  the runtime.  Decorators and module-level kernel definitions are
  fine — defining a kernel is not dispatching it.

The inverse boundary (nothing inside ``device/`` reaching back up into
subsystem logic) is reviewed, not machine-enforced.
"""

from __future__ import annotations

import ast
from typing import Tuple

from ..engine import SEVERITY_ERROR, FileContext, dotted_name

_BACKEND_TOUCHES = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend",
    "jax.device_put", "jax.device_get",
}
_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


class _DeviceRuleBase:
    severity = SEVERITY_ERROR
    requires_project = False    # per-file lexical rules (project API opt-out)

    def scope(self, parts: Tuple[str, ...]) -> bool:
        # device/ IS the sanctioned dispatch layer; lint/ holds these
        # rule names as data.  Everything else is client code.
        return "device" not in parts and "lint" not in parts


class BackendTouchRule(_DeviceRuleBase):
    rule_id = "DR001"
    description = ("backend init/enumeration (jax.devices & friends) "
                   "outside device/ — use get_runtime().devices()/platform()")

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    dotted_name(node.func) in _BACKEND_TOUCHES:
                yield (node.lineno, node.col_offset,
                       f"{dotted_name(node.func)}() outside device/ can "
                       "initialize the backend with no deadline and bypasses "
                       "the armed runtime — use get_runtime().devices() / "
                       ".platform()")


class BoxedCallRule(_DeviceRuleBase):
    rule_id = "DR002"
    description = ("boxed_call() outside device/ — submit through "
                   "get_runtime().run_boxed/submit_call instead")

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "boxed_call" or name.endswith(".boxed_call"):
                yield (node.lineno, node.col_offset,
                       f"{name}() outside device/ dispatches around the "
                       "runtime's fair queues — use get_runtime().run_boxed "
                       "(or submit_call/submit_sig_checks)")


class RuntimeJitRule(_DeviceRuleBase):
    rule_id = "DR003"
    description = ("jax.jit/pjit called as an expression inside a function "
                   "body outside device/ (bypasses arm-time AOT warm)")

    def check(self, ctx: FileContext):
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in func.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call) and \
                            dotted_name(node.func) in _JIT_NAMES:
                        yield (node.lineno, node.col_offset,
                               f"{dotted_name(node.func)}(...) staged inside "
                               "a function body outside device/ — hoist the "
                               "kernel to module level (or move the dispatch "
                               "into the device runtime) so arm-time AOT "
                               "warming sees it")


RULES = [BackendTouchRule(), BoxedCallRule(), RuntimeJitRule()]
