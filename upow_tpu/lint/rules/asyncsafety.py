"""AS — async-safety for the node's event loop (``node/``, ``ws/``).

One blocking call inside an ``async def`` stalls every connection the
node is serving: gossip stops fanning out, sync pages stop arriving, and
the WebSocket hub misses its heartbeats — with no error anywhere, just
latency.  The aiohttp shell must stay non-blocking end to end; anything
slow belongs in ``run_in_executor`` (the pattern the verify path already
uses for device dispatches).

AS001 flags calls to known-blocking APIs lexically inside ``async def``
(including nested sync helpers defined there, which almost always run on
the loop thread too): ``time.sleep``, the ``requests`` package, urllib
openers, ``socket`` connect/DNS, ``subprocess`` (use
``asyncio.create_subprocess_*``), and ``os.system``.

The blocking-call table itself lives in :mod:`upow_tpu.lint.project`
(``AS_BLOCKING``) and is shared with RC001, which generalizes this rule
interprocedurally across the whole package with an extended table (file
I/O, cross-thread joins).  AS001 stays lexical on purpose: it is the
fast, zero-false-positive core that fires even on a single file.
"""

from __future__ import annotations

import ast
from typing import Tuple

from ..engine import SEVERITY_ERROR, FileContext, dotted_name
from ..project import AS_BLOCKING as _BLOCKING
from ..project import BLOCKING_PREFIXES as _BLOCKING_PREFIXES

_SCOPE = {"node", "ws"}


class BlockingInAsyncRule:
    rule_id = "AS001"
    severity = SEVERITY_ERROR
    requires_project = False    # lexical by design; RC001 generalizes it
    description = "blocking call inside async def (node/ws event loop)"

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return bool(_SCOPE.intersection(parts[:-1]))

    def check(self, ctx: FileContext):
        for func in ast.walk(ctx.tree):
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                hint = _BLOCKING.get(name)
                if hint is None and name.startswith(_BLOCKING_PREFIXES):
                    hint = "use the shared aiohttp session"
                if hint:
                    yield (node.lineno, node.col_offset,
                           f"blocking {name}() inside async def stalls the "
                           f"whole event loop — {hint} (or run_in_executor)")


RULES = [BlockingInAsyncRule()]
