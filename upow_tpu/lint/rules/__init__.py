"""Rule registry. Import order fixes the --list-rules display order."""

from . import (asyncsafety, broadexcept, concurrency, consensus,
               devicepurity, dtypes, endianness, jitpurity)

ALL_RULES = (
    endianness.RULES
    + consensus.RULES
    + jitpurity.RULES
    + dtypes.RULES
    + asyncsafety.RULES
    + broadexcept.RULES
    + devicepurity.RULES
    + concurrency.RULES
)

__all__ = ["ALL_RULES"]
