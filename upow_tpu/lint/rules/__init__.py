"""Rule registry. Import order fixes the --list-rules display order."""

from . import (asyncsafety, broadexcept, consensus, devicepurity, dtypes,
               endianness, jitpurity)

ALL_RULES = (
    endianness.RULES
    + consensus.RULES
    + jitpurity.RULES
    + dtypes.RULES
    + asyncsafety.RULES
    + broadexcept.RULES
    + devicepurity.RULES
)

__all__ = ["ALL_RULES"]
