"""Rule registry. Import order fixes the --list-rules display order."""

from . import (asyncsafety, broadexcept, consensus, dtypes, endianness,
               jitpurity)

ALL_RULES = (
    endianness.RULES
    + consensus.RULES
    + jitpurity.RULES
    + dtypes.RULES
    + asyncsafety.RULES
    + broadexcept.RULES
)

__all__ = ["ALL_RULES"]
