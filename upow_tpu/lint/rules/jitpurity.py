"""JP — jit-purity.

A function under ``@jax.jit`` traces once per static signature; Python
control flow and host syncs inside it either crash at trace time
(``TracerBoolConversionError``) or — worse — silently bake a data
-dependent decision into the compiled program or force a device->host
round trip per call, which is exactly how the ≥1 GH/s sha256 and ≥100k
sig-verify/s targets regress to eager-speed without any test failing.

The checker runs a small taint analysis per decorated function:

* **Traced names** start as the function's parameters minus
  ``static_argnames`` / ``static_argnums`` (parsed from the decorator).
* Assignments propagate taint; so do for-loop targets over tainted
  iterables.
* **Taint breakers**: ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size``
  and ``len(x)`` are static under tracing (Python ints), so expressions
  built from them — e.g. ``assert n % 128 == 0`` with
  ``n = q.shape[1]`` — are NOT flagged.
* Nested ``def``/``lambda`` bodies are analyzed with their own parameters
  treated as traced (the ``shard_map``/``pallas_call`` body pattern).

Rules:

* JP001 — ``if`` / ``while`` / ``assert`` / conditional expression whose
  test involves a traced value.
* JP002 — host sync on a traced value: ``float()`` / ``int()`` /
  ``bool()``, ``.item()`` / ``.tolist()``, ``np.asarray`` / ``np.array``.
* JP003 — ``jnp.array(...)`` construction inside a jitted function
  (warning): prefer ``jnp.asarray`` (no-copy for arrays) or hoisting the
  constant out of the traced body.

Helpers *called from* a jitted function are not followed — this is a
commit-time tripwire for the decorated surfaces, not an interprocedural
analyzer; ``jax.checking_leaks`` remains the runtime backstop.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..engine import SEVERITY_ERROR, SEVERITY_WARNING, FileContext, dotted_name

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_NP_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _jit_static_info(func: ast.AST) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) if ``func`` is jit-decorated."""
    for dec in getattr(func, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name in ("jax.jit", "jit"):
            names, nums = set(), set()
            if isinstance(dec, ast.Call):
                names, nums = _static_kwargs(dec)
            return names, nums
        if name in ("functools.partial", "partial") and isinstance(dec, ast.Call) \
                and dec.args and dotted_name(dec.args[0]) in ("jax.jit", "jit"):
            return _static_kwargs(dec)
    return None


def _static_kwargs(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


def _param_names(func) -> List[str]:
    a = func.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does ``node`` reference a traced name outside a static context?"""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False  # x.shape etc. are Python values under tracing
    if isinstance(node, ast.Call):
        callee = node.func
        if isinstance(callee, ast.Name) and callee.id == "len":
            return False  # len(traced) is static
    if isinstance(node, ast.Name):
        return node.id in tainted
    return any(_expr_tainted(child, tainted)
               for child in ast.iter_child_nodes(node))


def _assign_targets(target: ast.AST) -> Iterable[str]:
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            yield node.id


class _JitVisitor:
    """Single linear pass over one jitted function body."""

    def __init__(self, tainted: Set[str]):
        self.tainted = set(tainted)
        self.findings: List[Tuple[int, int, str, str]] = []  # +rule key

    def visit_body(self, body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            self._visit_stmt(stmt)

    # -- statements -------------------------------------------------------
    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                self._scan_expr(value)
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                if _expr_tainted(value, self.tainted):
                    for name in targets:
                        self.tainted.update(_assign_targets(name))
        elif isinstance(stmt, (ast.If, ast.While)):
            if _expr_tainted(stmt.test, self.tainted):
                self.findings.append((
                    stmt.test.lineno, stmt.test.col_offset, "JP001",
                    f"Python `{'if' if isinstance(stmt, ast.If) else 'while'}`"
                    " on a traced value inside @jax.jit — use jnp.where/"
                    "lax.cond/lax.while_loop, or mark the argument static"))
            self._scan_expr(stmt.test)
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            if _expr_tainted(stmt.test, self.tainted):
                self.findings.append((
                    stmt.lineno, stmt.col_offset, "JP001",
                    "assert on a traced value inside @jax.jit — asserts "
                    "must only touch static args or .shape-derived values "
                    "(use checkify for traced invariants)"))
            self._scan_expr(stmt.test)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter)
            if _expr_tainted(stmt.iter, self.tainted):
                self.tainted.update(_assign_targets(stmt.target))
                self.findings.append((
                    stmt.iter.lineno, stmt.iter.col_offset, "JP001",
                    "Python loop over a traced value inside @jax.jit — "
                    "iteration count must be static (use lax.fori_loop/"
                    "scan for traced trip counts)"))
            self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _JitVisitor(self.tainted | set(_param_names(stmt)))
            inner.visit_body(stmt.body)
            self.findings.extend(inner.findings)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            self.visit_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.visit_body(stmt.body)
            for h in stmt.handlers:
                self.visit_body(h.body)
            self.visit_body(stmt.orelse)
            self.visit_body(stmt.finalbody)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._scan_expr(stmt.value)

    # -- expressions ------------------------------------------------------
    def _scan_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.IfExp) and \
                    _expr_tainted(node.test, self.tainted):
                self.findings.append((
                    node.lineno, node.col_offset, "JP001",
                    "conditional expression on a traced value inside "
                    "@jax.jit — use jnp.where/lax.select"))
            elif isinstance(node, ast.Lambda):
                inner = _JitVisitor(self.tainted | set(_param_names(node)))
                inner._scan_expr(node.body)
                self.findings.extend(inner.findings)
            elif isinstance(node, ast.Call):
                self._scan_call(node)

    def _scan_call(self, node: ast.Call) -> None:
        func = node.func
        args_tainted = any(_expr_tainted(a, self.tainted) for a in node.args)
        if isinstance(func, ast.Name) and func.id in _HOST_CASTS and args_tainted:
            self.findings.append((
                node.lineno, node.col_offset, "JP002",
                f"{func.id}() on a traced value inside @jax.jit forces a "
                "host sync (TracerBoolConversionError or a blocking "
                "transfer) — keep it on device or mark the arg static"))
        elif isinstance(func, ast.Attribute) and func.attr in _HOST_METHODS \
                and _expr_tainted(func.value, self.tainted):
            self.findings.append((
                node.lineno, node.col_offset, "JP002",
                f".{func.attr}() on a traced value inside @jax.jit is a "
                "blocking device->host transfer"))
        else:
            name = dotted_name(func)
            if name in _NP_SYNCS and args_tainted:
                self.findings.append((
                    node.lineno, node.col_offset, "JP002",
                    f"{name}() on a traced value inside @jax.jit "
                    "materializes on host — use jnp equivalents"))
            elif name == "jnp.array":
                self.findings.append((
                    node.lineno, node.col_offset, "JP003",
                    "jnp.array(...) inside @jax.jit re-stages its argument "
                    "every trace — prefer jnp.asarray (no-copy) or hoist "
                    "the constant out of the jitted body"))


def _jit_findings(ctx: FileContext):
    for func in ast.walk(ctx.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = _jit_static_info(func)
        if info is None:
            continue
        static_names, static_nums = info
        params = _param_names(func)
        tainted = {p for i, p in enumerate(params)
                   if p not in static_names and i not in static_nums}
        visitor = _JitVisitor(tainted)
        visitor.visit_body(func.body)
        yield from visitor.findings


class _JitRuleBase:
    requires_project = False    # per-file lexical rules (project API opt-out)

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return True  # jit purity is an invariant everywhere

    def check(self, ctx: FileContext):
        for line, col, key, message in _jit_findings(ctx):
            if key == self.rule_id:
                yield line, col, message


class TracedBranchRule(_JitRuleBase):
    rule_id = "JP001"
    severity = SEVERITY_ERROR
    description = "Python control flow on a traced value inside @jax.jit"


class HostSyncRule(_JitRuleBase):
    rule_id = "JP002"
    severity = SEVERITY_ERROR
    description = "host sync (float()/int()/.item()/np.asarray) on a traced value inside @jax.jit"


class JnpArrayRule(_JitRuleBase):
    rule_id = "JP003"
    severity = SEVERITY_WARNING
    description = "jnp.array(...) construction inside @jax.jit (prefer jnp.asarray / hoisting)"


RULES = [TracedBranchRule(), HostSyncRule(), JnpArrayRule()]
