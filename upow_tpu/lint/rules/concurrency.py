"""RC — race/concurrency rules over the whole-package call graph.

The node mutates consensus-critical state from the asyncio event loop
*and* from background threads (device-runtime drainer, ``boxed_call``
workers, miner watchdog).  File-local rules cannot see that a coroutine
three calls up the stack is the thing a blocking helper stalls, or that
two writers of one attribute live in different execution worlds — so
this family runs on the :mod:`upow_tpu.lint.project` call graph
(``requires_project = True``; findings are yielded per file by
``check_project``).

Rules
-----
* **RC001** — blocking call reachable *transitively* from a coroutine.
  Interprocedural generalization of AS001: the table adds file I/O and
  blocking cross-thread waits (``run_boxed``/``boxed_call``), and the
  finding is reported at the blocking call site with the async path in
  the message.  Executor/to_thread boundaries break the path.
* **RC002** — attribute written on both an event-loop path and a thread
  path with at least one unguarded write (no ``with <threading lock>:``
  around it).  ``__init__`` writes are construction, not racing.
* **RC003** — a *threading* lock held across an ``await``: every other
  acquirer (including thread-side ones) now waits on arbitrary loop
  latency, and a second acquisition on the same loop deadlocks.
* **RC004** — fire-and-forget leak: ``create_task``/``ensure_future``
  result dropped on the floor (exceptions vanish, no cancellation
  path), or a coroutine called as a bare statement and never awaited.
* **RC005** — loop-affine API (``asyncio.Queue``/``Event`` attributes,
  ``create_task``/``get_event_loop``) touched from a pure-thread
  function; ``call_soon_threadsafe``/``run_coroutine_threadsafe`` are
  the sanctioned boundary and exempt.

Known call-graph limitations (documented in docs/STATIC_ANALYSIS.md):
no dynamic dispatch, no decorator unwrapping, attribute receivers only
resolve through same-class constructor assignments.  Unresolvable calls
produce no edge — the family under-approximates rather than guesses.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Set, Tuple

from ..engine import SEVERITY_ERROR
from ..project import (
    LOOP,
    LOOP_AFFINE_ATTR_KINDS,
    LOOP_AFFINE_CALLS,
    LOCK_KINDS,
    THREAD,
    AS_BLOCKING,
    ProjectContext,
    blocking_reason,
)

#: AS001's home turf: depth-0 findings there belong to AS001, not RC001.
_AS_SCOPE = {"node", "ws"}

#: Task-spawning method names matched on the last dotted segment so
#: ``loop.create_task`` / ``self._loop.create_task`` are caught too.
_TASK_SPAWNERS = {"create_task", "ensure_future"}

_BOUNDARY_METHODS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}


def _rc_scope(parts: Tuple[str, ...]) -> bool:
    # Package-wide, except the linter itself (its fixtures and tables
    # mention blocking calls by name).
    return "lint" not in parts


class _ProjectRule:
    severity = SEVERITY_ERROR
    requires_project = True

    def scope(self, parts: Tuple[str, ...]) -> bool:
        return _rc_scope(parts)

    def check(self, ctx) -> Iterable:
        # File-local pass is a no-op; everything happens in
        # check_project once per run.
        return ()


class TransitiveBlockingRule(_ProjectRule):
    rule_id = "RC001"
    description = ("blocking call on an event-loop path "
                   "(transitive, whole-package)")

    _MAX_DEPTH = 8

    def check_project(self, proj: ProjectContext):
        memo: Dict[str, Optional[tuple]] = {}

        def witness(fid: str, depth: int) -> Optional[tuple]:
            """(rel, line, col, canon, hint, chain) of the first
            blocking call reachable from ``fid`` via sync edges."""
            if fid in memo:
                return memo[fid]
            if depth > self._MAX_DEPTH:
                return None
            memo[fid] = None            # cycle guard
            fn = proj.functions[fid]
            for call in fn.calls:
                hint = blocking_reason(call.canon)
                if hint:
                    w = (fn.rel, call.lineno, call.col, call.canon, hint,
                         (fn.qualname,))
                    memo[fid] = w
                    return w
            for call in fn.calls:
                tgt = proj.function(call.target)
                if tgt is None or tgt.is_async:
                    continue
                w = witness(tgt.fid, depth + 1)
                if w is not None:
                    w2 = w[:5] + ((fn.qualname,) + w[5],)
                    memo[fid] = w2
                    return w2
            return None

        seen: Set[Tuple[str, int, int]] = set()
        for fn in sorted(proj.iter_functions(), key=lambda f: f.fid):
            if not fn.is_async:
                continue
            for call in fn.calls:
                hint = blocking_reason(call.canon)
                if hint and not call.awaited:
                    # depth 0: AS001 already owns its own table in
                    # node/ws; RC001 adds the extended entries there
                    # and everything elsewhere.
                    parts = tuple(fn.rel.split("/"))
                    if call.canon in AS_BLOCKING and \
                            set(parts[:-1]) & _AS_SCOPE:
                        continue
                    key = (fn.rel, call.lineno, call.col)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield (fn.rel, call.lineno, call.col,
                           f"blocking {call.canon}() inside async "
                           f"{fn.qualname} stalls the event loop — {hint}")
                    continue
                tgt = proj.function(call.target)
                if tgt is None or tgt.is_async:
                    continue
                w = witness(tgt.fid, 1)
                if w is None:
                    continue
                rel, line, col, canon, hint, chain = w
                key = (rel, line, col)
                if key in seen:
                    continue
                seen.add(key)
                path = " → ".join(chain)
                yield (rel, line, col,
                       f"blocking {canon}() reached from async "
                       f"{fn.qualname} via {path} — {hint} (or cross the "
                       f"boundary with run_in_executor/to_thread)")


class CrossThreadWriteRule(_ProjectRule):
    rule_id = "RC002"
    description = ("attribute written on both loop and thread paths "
                   "without a lock")

    def check_project(self, proj: ProjectContext):
        for (modkey, _name), ci in sorted(proj.classes.items(),
                                          key=lambda kv: kv[1].rel):
            by_attr: Dict[str, list] = {}
            for w in ci.attr_writes:
                if w.in_init:
                    continue
                if ci.attr_types.get(w.attr) is not None:
                    continue        # lock/queue/executor plumbing itself
                by_attr.setdefault(w.attr, []).append(w)
            for attr, writes in sorted(by_attr.items()):
                loop_side, thread_side, unguarded = [], [], []
                for w in writes:
                    fn = proj.function(w.fid)
                    if fn is None:
                        continue
                    guarded = any(
                        proj.attr_type(fn, g) in LOCK_KINDS
                        for g in w.guards)
                    if LOOP in fn.colors:
                        loop_side.append((w, fn, guarded))
                    if THREAD in fn.colors:
                        thread_side.append((w, fn, guarded))
                    if not guarded and fn.colors:
                        unguarded.append((w, fn))
                if not loop_side or not thread_side or not unguarded:
                    continue
                w, fn = unguarded[0]
                loop_fn = loop_side[0][1].qualname
                thread_fn = thread_side[0][1].qualname
                yield (fn.rel, w.lineno, w.col,
                       f"self.{attr} written on an event-loop path "
                       f"({loop_fn}) and a thread path ({thread_fn}) "
                       f"with no threading.Lock guard — serialize via a "
                       f"lock, a queue, or call_soon_threadsafe")


class LockAcrossAwaitRule(_ProjectRule):
    rule_id = "RC003"
    description = "threading lock held across an await"

    def check_project(self, proj: ProjectContext):
        for fn in sorted(proj.iter_functions(), key=lambda f: f.fid):
            reported: Set[Tuple[str, ...]] = set()
            for ha in fn.held_awaits:
                if ha.lock in reported:
                    continue
                kind = proj.attr_type(fn, ha.lock)
                if kind not in LOCK_KINDS:
                    continue
                reported.add(ha.lock)
                lock_name = ".".join(ha.lock[1:]) or ha.lock[-1]
                yield (fn.rel, ha.lineno, ha.col,
                       f"threading lock {lock_name!r} held across await "
                       f"in {fn.qualname}: loop latency leaks into every "
                       f"other acquirer and re-entry deadlocks — release "
                       f"before awaiting or use asyncio.Lock")


class TaskLeakRule(_ProjectRule):
    rule_id = "RC004"
    description = ("fire-and-forget task/coroutine leak "
                   "(handle dropped / never awaited)")

    def check_project(self, proj: ProjectContext):
        for fn in sorted(proj.iter_functions(), key=lambda f: f.fid):
            for call in fn.calls:
                if not call.is_stmt or call.awaited:
                    continue
                last = call.canon.rsplit(".", 1)[-1]
                if last in _TASK_SPAWNERS:
                    yield (fn.rel, call.lineno, call.col,
                           f"{last}() result dropped in {fn.qualname}: "
                           f"exceptions vanish and the task cannot be "
                           f"cancelled — keep the handle and retrieve "
                           f"its exception (or use the node's _spawn)")
                    continue
                tgt = proj.function(call.target)
                if tgt is not None and tgt.is_async:
                    yield (fn.rel, call.lineno, call.col,
                           f"coroutine {tgt.qualname}() called as a bare "
                           f"statement in {fn.qualname} is never awaited "
                           f"— nothing runs; await it or schedule it as "
                           f"a task")


class LoopAffinityRule(_ProjectRule):
    rule_id = "RC005"
    description = "loop-affine asyncio API touched from a thread path"

    def check_project(self, proj: ProjectContext):
        for fn in sorted(proj.iter_functions(), key=lambda f: f.fid):
            if THREAD not in fn.colors or LOOP in fn.colors:
                continue
            for call in fn.calls:
                last = call.canon.rsplit(".", 1)[-1]
                if last in _BOUNDARY_METHODS:
                    continue
                hint = LOOP_AFFINE_CALLS.get(call.canon)
                if hint is None and last in _TASK_SPAWNERS and \
                        "." in call.name:
                    hint = "schedule via run_coroutine_threadsafe"
                if hint is None:
                    nparts = call.name.split(".")
                    if nparts[0] == "self" and len(nparts) == 3:
                        kind = proj.attr_type(fn, ("self", nparts[1]))
                        if kind in LOOP_AFFINE_ATTR_KINDS:
                            hint = ("asyncio primitives are not "
                                    "thread-safe; marshal through "
                                    "call_soon_threadsafe or a "
                                    "queue.Queue")
                if hint:
                    yield (fn.rel, call.lineno, call.col,
                           f"{call.canon}() touched from thread-side "
                           f"{fn.qualname} — {hint}")


RULES = [
    TransitiveBlockingRule(),
    CrossThreadWriteRule(),
    LockAcrossAwaitRule(),
    TaskLeakRule(),
    LoopAffinityRule(),
]
