"""Runtime concurrency sanitizer: the dynamic half of the RC family.

The static rules prove what the call graph shows; this module catches
what it cannot — a jit trace that compiles inline, a third-party call
that blocks, a task whose exception dies un-retrieved in a branch the
linter could not color.  It is an opt-in test/CI harness (installed by
the tier-1/chaos conftest fixture, never by product code) with four
mechanisms:

* **Slow-callback watchdog** — every event-loop callback/task step is
  timed by wrapping ``asyncio.events.Handle._run``; a sampler thread
  additionally captures the live stack (``sys._current_frames()``) of
  a callback still running past the threshold, so the finding names
  the blocking frame, not just the coroutine.  Each trip records a
  finding and emits a structured ``sanitizer.blocked_loop`` telemetry
  event.
* **Un-retrieved task exceptions** — asyncio reports these through
  ``loop.call_exception_handler`` (often from ``Task.__del__`` long
  after the fact); the class-level patch records them as findings so a
  test that leaked one fails *now*.
* **Never-awaited coroutines** — surfaced via a forced ``gc.collect()``
  under ``warnings.catch_warnings`` at fixture teardown
  (:meth:`ConcurrencySanitizer.flush_never_awaited`).
* **Thread-affinity assertions** — the device runtime calls
  :func:`check_blocking_wait` at its submit/drain seam
  (``run_boxed``/``boxed_call``); if that seam is crossed from a
  thread that is running an event loop, the sanitizer trips.

Findings carry ``product`` attribution: a blocked-loop trip whose
callback (or live stack) lands in ``upow_tpu/`` product code is a
product bug; test code legitimately blocks its own loop (jax compiles,
synchronous fixtures), so the conftest gate fails only on
product-attributed trips.  Stdlib-only, like the rest of the linter.
"""

from __future__ import annotations

import asyncio
import asyncio.base_events
import asyncio.events
import gc
import sys
import threading
import time
import traceback
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_PRODUCT_MARKER = "upow_tpu"
_SELF_MARKERS = ("upow_tpu/lint", "upow_tpu\\lint")


@dataclass
class SanitizerFinding:
    kind: str                  # blocked_loop | task_exception |
    #                            never_awaited | affinity
    detail: str
    product: bool              # attributed to product (non-lint) code
    stack: str = ""
    ts: float = field(default_factory=time.time)

    def __str__(self) -> str:
        tag = "product" if self.product else "test"
        out = f"[{self.kind}/{tag}] {self.detail}"
        if self.stack:
            out += "\n" + self.stack
        return out


def _is_product_file(filename: str) -> bool:
    if not filename:
        return False
    norm = filename.replace("\\", "/")
    if any(m.replace("\\", "/") in norm for m in _SELF_MARKERS):
        return False
    return f"/{_PRODUCT_MARKER}/" in norm or \
        norm.startswith(f"{_PRODUCT_MARKER}/")


def _describe_handle(handle) -> Tuple[str, bool]:
    """(human description, is-product) for a loop callback handle."""
    cb = getattr(handle, "_callback", None)
    task = getattr(cb, "__self__", None)
    if isinstance(task, asyncio.Task):
        try:
            coro = task.get_coro()
            code = getattr(coro, "cr_code", None)
            if code is not None:
                name = getattr(code, "co_qualname", code.co_name)
                return (f"task {name} "
                        f"({code.co_filename}:{code.co_firstlineno})",
                        _is_product_file(code.co_filename))
        # describing a finding must never crash the wrapped loop
        # callback it runs inside of; fall back to repr
        except Exception:  # upowlint: disable=BE001
            pass
        return (repr(task), False)
    code = getattr(cb, "__code__", None)
    if code is not None:
        name = getattr(code, "co_qualname", code.co_name)
        return (f"callback {name} "
                f"({code.co_filename}:{code.co_firstlineno})",
                _is_product_file(code.co_filename))
    return (repr(cb), False)


_CORO_FLAGS = 0x0080 | 0x0200   # CO_COROUTINE | CO_ASYNC_GENERATOR


def _blame_coroutine(frame) -> Optional[bool]:
    """Walk a live stack outward to the nearest *coroutine* frame and
    return its product attribution (None when no coroutine frame is on
    the stack).  The coroutine is the responsible party: a test
    coroutine driving sync product code on its own loop is a test
    choice, while a product coroutine stuck anywhere is a product bug."""
    while frame is not None:
        if frame.f_code.co_flags & _CORO_FLAGS:
            return _is_product_file(frame.f_code.co_filename)
        frame = frame.f_back
    return None


class ConcurrencySanitizer:
    """Installable event-loop instrumentation; see module docstring.

    One instance is installed at a time (module-level :func:`install` /
    :func:`uninstall`); findings accumulate until :meth:`drain`.
    """

    def __init__(self, blocked_loop_threshold: float = 1.0):
        self.threshold = float(blocked_loop_threshold)
        self._findings: List[SanitizerFinding] = []
        self._lock = threading.Lock()
        # thread id -> (t0, handle) while a callback is mid-flight
        self._running: Dict[int, Tuple[float, Any]] = {}
        self._flagged: set = set()     # id(handle) already reported live
        self._orig_run = None
        self._orig_handler = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.saw_loop_activity = False

    # -- recording ---------------------------------------------------------

    def _record(self, kind: str, detail: str, product: bool,
                stack: str = "") -> None:
        with self._lock:
            self._findings.append(SanitizerFinding(
                kind=kind, detail=detail, product=product, stack=stack))

    def drain(self) -> List[SanitizerFinding]:
        with self._lock:
            out, self._findings = self._findings, []
            self.saw_loop_activity = False
            return out

    # -- blocked-loop watchdog ---------------------------------------------

    def _emit_blocked(self, detail: str, product: bool,
                      stack: str, elapsed: float) -> None:
        self._record("blocked_loop",
                     f"{detail} blocked the event loop for "
                     f"{elapsed:.3f}s (threshold {self.threshold:.3f}s)",
                     product, stack)
        try:
            from .. import telemetry

            telemetry.event("sanitizer.blocked_loop", callback=detail,
                            seconds=round(elapsed, 3), product=product,
                            stack=stack[-2000:])
        # telemetry is best-effort: the finding itself is already
        # recorded, and a telemetry failure must not mask it
        except Exception:  # upowlint: disable=BE001
            pass

    def _wrapped_run(self, handle):
        tid = threading.get_ident()
        if tid in self._running:        # nested (re-entrant) — passthrough
            return self._orig_run(handle)
        self.saw_loop_activity = True
        t0 = time.perf_counter()
        self._running[tid] = (t0, handle)
        try:
            return self._orig_run(handle)
        finally:
            self._running.pop(tid, None)
            elapsed = time.perf_counter() - t0
            if elapsed >= self.threshold:
                if id(handle) in self._flagged:
                    self._flagged.discard(id(handle))
                else:
                    detail, product = _describe_handle(handle)
                    self._emit_blocked(detail, product, "", elapsed)

    def _watch(self) -> None:
        interval = max(0.01, self.threshold / 4.0)
        while not self._stop.wait(interval):
            now = time.perf_counter()
            for tid, (t0, handle) in list(self._running.items()):
                if now - t0 < self.threshold or id(handle) in self._flagged:
                    continue
                self._flagged.add(id(handle))
                frame = sys._current_frames().get(tid)
                stack = "".join(traceback.format_stack(frame)) \
                    if frame is not None else ""
                detail, product = _describe_handle(handle)
                # live stack beats callback attribution when it shows a
                # coroutine frame — blame lands on the coroutine that is
                # actually stuck, not on whoever scheduled the callback
                if frame is not None:
                    blame = _blame_coroutine(frame)
                    if blame is not None:
                        product = blame
                self._emit_blocked(detail, product, stack, now - t0)

    # -- un-retrieved task exceptions --------------------------------------

    def _wrapped_exception_handler(self, loop, context):
        message = context.get("message", "") or ""
        if "never retrieved" in message:
            src = context.get("task") or context.get("future")
            exc = context.get("exception")
            product = False
            task = src if isinstance(src, asyncio.Task) else None
            if task is not None:
                code = getattr(task.get_coro(), "cr_code", None)
                if code is not None:
                    product = _is_product_file(code.co_filename)
            self._record("task_exception",
                         f"{message}: {src!r} -> {exc!r}", product)
        return self._orig_handler(loop, context)

    # -- never-awaited coroutines ------------------------------------------

    def flush_never_awaited(self) -> None:
        """Force 'coroutine ... was never awaited' warnings still held
        in GC cycles out and record them as findings.  Coroutines whose
        refcount hits zero during the test warn immediately instead —
        the conftest fixture scans pytest's recorded warnings and feeds
        those through :meth:`record_never_awaited`.

        Only the young generations are collected: a cycle-held coroutine
        abandoned moments ago is still young, and a full-heap collect
        per test is measurably expensive once the suite has built up a
        large object graph (jax keeps a lot alive)."""
        if not self.saw_loop_activity:
            return
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            gc.collect(1)
        for w in caught:
            self.record_never_awaited(str(w.message))

    def record_never_awaited(self, message: str) -> None:
        if "was never awaited" in message:
            # the RuntimeWarning carries no filename for the coroutine
            # itself; conservatively treat every leak as failing — a
            # never-awaited coroutine is a bug wherever it lives
            self._record("never_awaited", message, product=True)

    # -- thread-affinity at the device-runtime seam ------------------------

    def check_blocking_wait(self, site: str) -> None:
        """Called by DeviceRuntime.run_boxed/boxed_call: blocking this
        thread is only legal when no event loop runs on it.

        Responsibility lies with the nearest enclosing *coroutine*
        frame — the async code that chose to call a sync blocking API
        on the loop — not with the sync product function itself (tests
        legitimately drive sync entry points from their own loop)."""
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return
        frame = sys._getframe(1)
        stack = "".join(traceback.format_stack(frame))
        blame = _blame_coroutine(frame)
        product = True if blame is None else blame
        self._record(
            "affinity",
            f"{site} would block an event-loop thread (cross the seam "
            f"with run_in_executor / await the runtime future instead)",
            product=product, stack=stack)

    # -- install/uninstall -------------------------------------------------

    def install(self) -> None:
        if self._orig_run is not None:
            raise RuntimeError("sanitizer already installed")
        self._orig_run = asyncio.events.Handle._run
        sanitizer = self

        def run(handle):
            return sanitizer._wrapped_run(handle)

        asyncio.events.Handle._run = run

        self._orig_handler = \
            asyncio.base_events.BaseEventLoop.call_exception_handler

        def handler(loop, context):
            return sanitizer._wrapped_exception_handler(loop, context)

        asyncio.base_events.BaseEventLoop.call_exception_handler = handler

        self._stop.clear()
        self._watchdog = threading.Thread(
            target=self._watch, name="upow-sanitizer-watchdog", daemon=True)
        self._watchdog.start()

    def uninstall(self) -> None:
        if self._orig_run is None:
            return
        asyncio.events.Handle._run = self._orig_run
        asyncio.base_events.BaseEventLoop.call_exception_handler = \
            self._orig_handler
        self._orig_run = None
        self._orig_handler = None
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
            self._watchdog = None


# --------------------------------------------------------------------------
# Module-level singleton: product hooks must stay O(1) when inactive.
# --------------------------------------------------------------------------

_ACTIVE: Optional[ConcurrencySanitizer] = None


def active() -> Optional[ConcurrencySanitizer]:
    return _ACTIVE


def install(blocked_loop_threshold: float = 1.0) -> ConcurrencySanitizer:
    """Install a fresh sanitizer as the active one and return it."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("sanitizer already installed")
    san = ConcurrencySanitizer(blocked_loop_threshold=blocked_loop_threshold)
    san.install()
    _ACTIVE = san
    return san


def uninstall() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.uninstall()
        _ACTIVE = None


def check_blocking_wait(site: str) -> None:
    """Product-side hook (device runtime submit/drain seam): no-op
    unless a sanitizer is installed."""
    san = _ACTIVE
    if san is not None:
        san.check_blocking_wait(site)
