"""Opt-in kernel profiling: jax.profiler capture + XLA cost analysis.

Two capabilities, both off unless asked for (``UPOW_PROFILE_*`` /
``ProfilingConfig``), both safe to call when jax is absent or broken —
profiling must never take the node down:

* :func:`start` / :func:`stop` / :func:`status` — a process-wide
  ``jax.profiler`` capture session (xprof trace directory), driven by
  the ``/debug/profile?action=start|stop|status`` endpoint.  One
  capture at a time; a capture left running past
  ``max_capture_seconds`` is auto-closed on the next touch so a
  forgotten ``action=start`` can't fill the disk.
* :func:`analyze_cost` — per-compile XLA cost analysis
  (``fn.lower(*args).compile().cost_analysis()``): FLOPs / bytes
  accessed estimates recorded into :mod:`..telemetry.device` next to
  the compile-cache counters, so kernel-occupancy stalls have
  attributable arithmetic-intensity numbers.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..logger import get_logger
from ..telemetry import device as _device
from ..telemetry import event as _event

log = get_logger("profiling")

_lock = threading.Lock()
_session: dict = {}  # {trace_dir, started_at, max_seconds} while active


def _expire_locked(now: float) -> None:
    """Close an over-deadline capture (caller holds ``_lock``)."""
    if not _session:
        return
    limit = _session.get("max_seconds") or 0
    if limit and now - _session["started_at"] > limit:
        log.warning("profiler capture exceeded %.0fs; auto-stopping", limit)
        _stop_locked(reason="max_capture_seconds")


def _stop_locked(reason: str = "requested") -> dict:
    info = {"trace_dir": _session.get("trace_dir"),
            "seconds": round(time.monotonic()
                             - _session.get("started_at", 0.0), 3),
            "reason": reason}
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # teardown must not propagate to the endpoint
        log.warning("jax profiler stop failed: %s", e)
        info["error"] = f"{type(e).__name__}: {e}"[:200]
    _session.clear()
    _event("profile_capture_stopped", **info)
    return info


def start(trace_dir: str, max_seconds: float = 0.0) -> dict:
    """Begin a capture into ``trace_dir``.  Returns a status dict; on
    failure ``{"error": ...}`` rather than raising."""
    with _lock:
        _expire_locked(time.monotonic())
        if _session:
            return {"error": "capture already active",
                    "trace_dir": _session["trace_dir"]}
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            log.warning("jax profiler start failed: %s", e)
            return {"error": f"{type(e).__name__}: {e}"[:200]}
        _session.update(trace_dir=trace_dir,
                        started_at=time.monotonic(),
                        max_seconds=max_seconds)
        _event("profile_capture_started", trace_dir=trace_dir)
        return {"active": True, "trace_dir": trace_dir}


def stop() -> dict:
    """End the active capture; {"error": ...} when none is running."""
    with _lock:
        if not _session:
            return {"error": "no capture active"}
        return _stop_locked()


def status() -> dict:
    with _lock:
        _expire_locked(time.monotonic())
        if not _session:
            return {"active": False}
        return {"active": True, "trace_dir": _session["trace_dir"],
                "seconds": round(time.monotonic()
                                 - _session["started_at"], 3)}


def reset() -> None:
    """Forget any active session without touching jax (tests)."""
    with _lock:
        _session.clear()


def analyze_cost(kernel: str, fn, *args,
                 static_argnums=None) -> Optional[dict]:
    """AOT-compile ``fn(*args)`` and record its XLA cost analysis.

    ``fn`` may be jitted or plain (plain callables are wrapped).  The
    normalized numeric entries (``flops``, ``bytes accessed``, ...) are
    stored via :func:`telemetry.device.record_cost` and returned; any
    failure returns None — estimates are observability, never
    correctness.
    """
    try:
        import jax

        if not hasattr(fn, "lower"):
            # offline cost analysis lowers the kernel without dispatching;
            # the profiler is a dev tool outside the runtime's hot path
            fn = jax.jit(fn, static_argnums=static_argnums)  # upowlint: disable=DR003
        compiled = fn.lower(*args).compile()
        analysis = compiled.cost_analysis()
        # older jax returns a per-computation list; newest a flat dict
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if not isinstance(analysis, dict) or not analysis:
            return None
        clean = {k: float(v) for k, v in analysis.items()
                 if isinstance(v, (int, float))
                 and not isinstance(v, bool)}
        if not clean:
            return None
        _device.record_cost(kernel, clean)
        return clean
    except Exception as e:
        log.debug("cost analysis for %s failed: %s", kernel, e)
        return None
