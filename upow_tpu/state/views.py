"""Backend-independent chain-state views.

The consensus-critical read logic — the active-inode vote cascade,
balance/stake aggregation, fee math, UTXO fingerprints — is identical
whatever engine holds the tables.  :class:`StateViews` keeps that logic
in ONE place as pure functions over a small set of storage primitives
(``get_*``/``add_*`` methods touching the database), which each backend
implements in its own dialect:

* :class:`upow_tpu.state.storage.ChainState` — sqlite, this framework's
  native schema (denormalized amounts, JSON address arrays),
* :class:`upow_tpu.state.pg.PgChainState` — PostgreSQL, byte-exact to
  the reference's ``schema.sql`` for drop-in interop with an existing
  uPow database.

Primitives a backend must provide (the seam):
    get_transaction, get_transaction_info, get_output_amount,
    get_registered, get_ballot_by_recipient, _all_ballot_rows,
    get_multiple_address_stakes, get_spendable_outputs,
    get_stake_outputs, get_pending_spent_outpoints, _pending_decoded,
    get_transaction_block_timestamp, get_table_outpoints_hash,
    get_block_transaction_hashes, resolve_output_address,
    get_votes-related tables, add_transactions.

Every method cites its reference counterpart; the bodies were lifted
verbatim from the round-1/2 sqlite implementation (storage.py) when this
seam was cut for the Postgres backend.
"""

from __future__ import annotations

import json
import os
from decimal import Decimal
from typing import Dict, Iterable, List, Tuple, Union

from ..core.clock import timestamp as now_ts
from ..core.codecs import OutputType, TransactionType
from ..core.constants import SMALLEST
from ..core.rewards import round_up_decimal
from ..core.tx import CoinbaseTx, Tx

AnyTx = Union[Tx, CoinbaseTx]


class StateViews:
    """Shared pure logic over the storage primitives (see module doc)."""

    # ------------------------------------------------------------- fees ---

    async def tx_fees(self, tx: AnyTx) -> int:
        """fee = Σ input amounts − Σ output amounts (int smallest units).

        Memoized on the tx object: source amounts are content-addressed
        by (tx_hash, index) and therefore immutable for a given input
        set, so a tx's fee never changes — and block accept computes it
        three times per tx (rules check, reward sum, storage row)."""
        if tx.is_coinbase:
            return 0
        # scoped by the state's fees generation (bumped on reorg, like
        # _amount_cache_drop): a tx object held across a remove_blocks
        # must not keep a fee whose source tx no longer exists — the
        # gone-source -> fee=0 decision is consensus (storage.py note)
        gen = getattr(self, "_fees_gen", 0)
        memo = getattr(tx, "_fees_units", None)
        if memo is not None and memo[0] == gen:
            return memo[1]
        total_in = 0
        for i in tx.inputs:
            amount = await self.get_output_amount(i.tx_hash, i.index)
            if amount is None:
                return 0  # unresolvable input: not memoized (may appear)
            total_in += amount
        fee = tx.fees(total_in)
        tx._fees_units = (gen, fee)
        return fee

    def _bump_fees_gen(self) -> None:
        """Invalidate every outstanding per-object fee memo (reorg)."""
        self._fees_gen = getattr(self, "_fees_gen", 0) + 1

    # ----------------------------------------------------- transactions ---

    async def add_transaction(self, tx: AnyTx, block_hash: str) -> None:
        await self.add_transactions([tx], block_hash)

    async def get_transactions_info(self, tx_hashes: Iterable[str]) -> Dict[str, dict]:
        out = {}
        for h in tx_hashes:
            info = await self.get_transaction_info(h)
            if info is not None:
                out[h] = info
        return out

    # ------------------------------------------------------ fingerprints --

    async def get_unspent_outputs_hash(self) -> str:
        """UTXO-set fingerprint: sha256 over the sorted outpoint list —
        the cross-node state-equality oracle (reference database.py:827-830,
        logged every 10 blocks, exposed at GET /)."""
        return await self.get_table_outpoints_hash("unspent_outputs")

    async def get_full_state_hash(self) -> str:
        """Fingerprint over ALL UTXO-class tables (governance included) —
        what replay checks must compare: a divergence confined to e.g.
        the validator ballot leaves the wire-visible unspent_outputs
        fingerprint untouched."""
        import hashlib

        from .storage import _GOV_TABLES

        h = hashlib.sha256()
        for table in ("unspent_outputs",) + _GOV_TABLES:
            h.update(table.encode())
            h.update((await self.get_table_outpoints_hash(table)).encode())
        return h.hexdigest()

    # --------------------------------------------------- address views ----

    async def get_address_balance(self, address: str,
                                  check_pending_txs: bool = False) -> int:
        """Spendable balance in smallest units; ``check_pending_txs`` adds
        unconfirmed incoming REGULAR outputs (reference database.py:1138-1186)."""
        balance = sum(i.amount for i in await self.get_spendable_outputs(
            address, check_pending_txs=check_pending_txs))
        if check_pending_txs:
            for tx in (await self._pending_decoded()).values():
                for out in tx.outputs:
                    if out.address == address and out.output_type == OutputType.REGULAR:
                        balance += out.amount
        return balance

    async def get_address_stake(self, address: str,
                                check_pending_txs: bool = False) -> Decimal:
        """Staked coins as Decimal (governance ratio math is Decimal-exact;
        reference database.py:1189-1205)."""
        stake = sum(i.amount for i in await self.get_stake_outputs(
            address, check_pending_txs=check_pending_txs))
        stake = Decimal(stake) / SMALLEST
        if check_pending_txs:
            for tx in (await self._pending_decoded()).values():
                for out in tx.outputs:
                    if out.address == address and out.is_stake:
                        stake += Decimal(out.amount) / SMALLEST
        return stake

    # ------------------------------------------------------- governance ---

    async def is_inode_registered(self, address: str,
                                  check_pending_txs: bool = False) -> bool:
        return any(a == address for a, _ in await self.get_registered(
            "inode_registration_output", check_pending_txs))

    async def is_validator_registered(self, address: str,
                                      check_pending_txs: bool = False) -> bool:
        return any(a == address for a, _ in await self.get_registered(
            "validator_registration_output", check_pending_txs))

    async def get_votes_by_voter(self, table: str, voter: str,
                                 check_pending_txs: bool = False) -> List[dict]:
        """Standing votes cast BY ``voter`` (reference database.py:1557-1581
        get_delegates_spent_votes shape) — a filter over
        :meth:`_all_ballot_rows`, the single home of the voter rule."""
        rows = await self._all_ballot_rows(table, check_pending_txs)
        return [
            {"tx_hash": r["tx_hash"], "index": r["index"],
             "recipient": r["recipient"], "vote": r["vote"]}
            for r in rows if r["voter"] == voter
        ]

    async def get_validators_stake(self, validator: str,
                                   check_pending_txs: bool = False) -> Decimal:
        """Σ (vote × delegate stake) / 10 over the validator's ballot
        (reference database.py:1127-1136)."""
        ballot = await self.get_ballot_by_recipient(
            "validators_ballot", validator, check_pending_txs)
        total = Decimal(0)
        for entry in ballot:
            if entry["voter"] is None:
                continue
            stake = await self.get_address_stake(entry["voter"], check_pending_txs)
            total += entry["vote"] * stake / 10
        return round_up_decimal(total)

    async def get_inode_vote_ratio_by_address(self, inode: str,
                                              check_pending_txs: bool = False) -> Decimal:
        """Σ (vote × validator stake) / 10 over votes FOR this inode
        (reference database.py:1390-1418)."""
        ballot = await self.get_ballot_by_recipient(
            "inodes_ballot", inode, check_pending_txs)
        total = Decimal(0)
        for entry in ballot:
            if entry["voter"] is None:
                continue
            stake = await self.get_validators_stake(entry["voter"], check_pending_txs)
            total += entry["vote"] * stake / 10
        return round_up_decimal(total)

    async def get_active_inodes(self, check_pending_txs: bool = False) -> List[dict]:
        """Registered inodes with power/emission; active = emission >= 1% or
        registered within 48 h (reference database.py:1377-1388).

        The reference computes this through an O(inodes x votes x
        ballots) SQL cascade per block accept (database.py:1390-1426,
        SURVEY §3 hot loop #3).  Here it is three bulk reads + one
        batched stake query; the per-level round_up_decimal calls mirror
        the cascade's rounding exactly (per-validator stake rounded,
        then per-inode power rounded)."""
        pending = (await self.get_pending_spent_outpoints()) \
            if check_pending_txs else set()
        registered = await self.get_registered(
            "inode_registration_output", check_pending_txs, pending=pending)
        vrows = await self._all_ballot_rows(
            "validators_ballot", check_pending_txs, pending=pending)
        stakes = await self.get_multiple_address_stakes(
            {r["voter"] for r in vrows if r["voter"]}, check_pending_txs,
            pending=pending)
        vstake_raw: Dict[str, Decimal] = {}
        for r in vrows:
            if r["voter"] is None:
                continue
            vstake_raw[r["recipient"]] = vstake_raw.get(
                r["recipient"], Decimal(0)) \
                + r["vote"] * stakes.get(r["voter"], Decimal(0)) / 10
        validators_stake = {k: round_up_decimal(v)
                            for k, v in vstake_raw.items()}
        irows = await self._all_ballot_rows(
            "inodes_ballot", check_pending_txs, pending=pending)
        power_raw: Dict[str, Decimal] = {}
        for r in irows:
            if r["voter"] is None:
                continue
            power_raw[r["recipient"]] = power_raw.get(
                r["recipient"], Decimal(0)) \
                + r["vote"] * validators_stake.get(r["voter"], Decimal(0)) / 10
        details = []
        for address, registered_at in registered:
            details.append({
                "wallet": address,
                "power": round_up_decimal(power_raw.get(address, Decimal(0))),
                "registered_at": registered_at,
            })
        total_power = sum(d["power"] for d in details)
        active = []
        for d in details:
            emission = (
                d["power"] / total_power * 100 if total_power > 0 else d["power"]
            )
            d["emission"] = round_up_decimal(emission, round_up_length="0.01")
            is_active = d["emission"] >= 1 or (now_ts() - d["registered_at"]) <= 48 * 3600
            if is_active:
                active.append(d)
        return active

    async def is_revoke_valid(self, tx_hash: str) -> bool:
        """A vote can be revoked 48 h after the block that recorded it
        (reference database.py:1073-1076)."""
        ts = await self.get_transaction_block_timestamp(tx_hash)
        return ts is not None and now_ts() - ts >= 48 * 3600

    async def get_delegates_spent_votes(self, address: str,
                                        check_pending_txs: bool = False) -> List[dict]:
        """Standing delegate votes by this address (reference
        database.py:1557-1581) — unstake requires these released."""
        return await self.get_votes_by_voter(
            "validators_ballot", address, check_pending_txs)

    async def get_delegates_all_power(self, address: str,
                                      check_pending_txs: bool = False) -> list:
        """Unspent voting power plus standing votes (database.py:1583-1587)."""
        power = list(await self.get_delegates_voting_power(address, check_pending_txs))
        power.extend(
            (v["tx_hash"], v["index"])
            for v in await self.get_delegates_spent_votes(address, check_pending_txs))
        return power

    async def get_validators_spent_votes(self, address: str,
                                         check_pending_txs: bool = False) -> List[dict]:
        """Standing inode votes cast by this validator (the validator's
        analog of get_delegates_spent_votes)."""
        return await self.get_votes_by_voter(
            "inodes_ballot", address, check_pending_txs)

    async def get_pending_stake_transactions(self, address: str) -> List[Tx]:
        """Pending txs that stake for this address (database.py:1157-1172)."""
        return [tx for tx in (await self._pending_decoded()).values()
                if any(o.address == address and o.is_stake for o in tx.outputs)]

    async def get_pending_vote_as_delegate_transactions(self, address: str) -> List[Tx]:
        """Pending VOTE_AS_DELEGATE txs whose first input is this address
        (database.py:1174-1187)."""
        out = []
        for tx in (await self._pending_decoded()).values():
            if tx.transaction_type != TransactionType.VOTE_AS_DELEGATE or tx.is_coinbase:
                continue
            if not tx.inputs:
                continue
            first = await self.resolve_output_address(
                tx.inputs[0].tx_hash, tx.inputs[0].index)
            if first == address:
                out.append(tx)
        return out

    # ---------------------------------------------------- explorer views --

    async def get_block_nice_transactions(self, block_hash: str) -> List[dict]:
        # a tx can vanish between the hash listing and the per-tx lookup
        # under a concurrent reorg: drop the None, never embed null
        nice = [
            await self.get_nice_transaction(h)
            for h in await self.get_block_transaction_hashes(block_hash)
        ]
        return [t for t in nice if t is not None]

    # ---------------------------------------------------------- emission --

    def record_emission(self, block_no: int, details: dict) -> None:
        """Per-block reward audit sidecar (reference emission_details.json)."""
        if self.emission_path is None:
            return
        data = {}
        if os.path.exists(self.emission_path):
            with open(self.emission_path) as f:
                data = json.load(f)
        data[str(block_no)] = details
        tmp = self.emission_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.emission_path)
