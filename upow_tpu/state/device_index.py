"""Device-resident UTXO membership index (SURVEY.md §2.2, asyncpg row).

The block-accept hot path tests every input outpoint against the unspent
set (reference manager.py:531-615 does per-class SQL set-diffs).  Here the
common case runs on device: outpoints are fingerprinted to 32 bits
(first 4 bytes of sha256(tx_hash || index)), kept as ONE sorted int32
array in HBM, and a whole block's inputs are tested with a single
``searchsorted`` + gather-compare.

The fingerprint is a *prefilter*, not the consensus decision:

* fingerprint miss  -> outpoint is definitely NOT unspent (exact),
* fingerprint hit   -> "maybe" — the host double-checks against storage.

With ~1M UTXOs the false-positive rate is ~0.02% per lookup, so an
8k-input block escalates a handful of host lookups while the other
thousands short-circuit on device.  Rebuilds are a numpy sort (ms),
refreshed per accepted block; the array is reconstructible from storage
at any height (checkpoint/resume story, SURVEY.md §5).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Outpoint = Tuple[str, int]


def fingerprint(outpoint: Outpoint) -> int:
    tx_hash, index = outpoint
    digest = hashlib.sha256(bytes.fromhex(tx_hash) + index.to_bytes(1, "little")).digest()
    return int.from_bytes(digest[:4], "little", signed=True)  # int32 reinterpret


@jax.jit
def _member_mask(sorted_keys, queries):
    pos = jnp.searchsorted(sorted_keys, queries)
    pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    return sorted_keys[pos] == queries


class DeviceUtxoIndex:
    """Sorted-fingerprint membership prefilter, one per UTXO-class table."""

    def __init__(self, outpoints: Iterable[Outpoint] = ()):
        self._exact = set(outpoints)
        self._dirty = True
        self._keys = None

    def __len__(self):
        return len(self._exact)

    def add(self, outpoints: Iterable[Outpoint]) -> None:
        self._exact.update(outpoints)
        self._dirty = True

    def remove(self, outpoints: Iterable[Outpoint]) -> None:
        self._exact.difference_update(outpoints)
        self._dirty = True

    def _device_keys(self):
        if self._dirty:
            keys = np.fromiter(
                (fingerprint(o) for o in self._exact), dtype=np.int32,
                count=len(self._exact),
            )
            keys.sort()
            # pad to a non-empty power-of-two length to bound recompiles
            n = max(1, 1 << (len(keys) - 1).bit_length()) if len(keys) else 1
            pad = np.full(n - len(keys), np.iinfo(np.int32).max, dtype=np.int32)
            self._keys = jnp.asarray(np.concatenate([keys, pad]))
            self._dirty = False
        return self._keys

    def contains_batch(self, outpoints: Sequence[Outpoint]) -> List[bool]:
        """Exact membership for a batch: device prefilter + host refinement."""
        if not outpoints:
            return []
        queries = np.fromiter(
            (fingerprint(o) for o in outpoints), dtype=np.int32,
            count=len(outpoints),
        )
        n = 1 << (len(queries) - 1).bit_length() if len(queries) else 1
        padded = np.concatenate([
            queries, np.full(n - len(queries), np.iinfo(np.int32).min, np.int32)])
        maybe = np.asarray(_member_mask(self._device_keys(), jnp.asarray(padded)))[
            : len(outpoints)]
        # fingerprint hit -> host-exact confirmation (collisions possible)
        return [bool(m) and (o in self._exact) for m, o in zip(maybe, outpoints)]

    def missing(self, outpoints: Sequence[Outpoint]) -> List[Outpoint]:
        present = self.contains_batch(outpoints)
        return [o for o, ok in zip(outpoints, present) if not ok]
