"""HBM-resident UTXO index: device membership + value store (ISSUE 11).

Earlier rounds kept 64-bit XOR-fold fingerprints on device as a
*prefilter* and resolved every hit through a host-side exact map — one
Python dict walk per probed outpoint, which is exactly the per-tx host
round-trip the accept path must shed to reach the PAPER.md target.
This round promotes the structure to a true resident index:

* **128-bit effective fingerprints.**  The sorted key is the historical
  64-bit XOR-fold (``fingerprint_batch`` — bit-identical to previous
  rounds); each entry additionally carries an independent 64-bit
  *check* fingerprint (``check_batch``, distinct odd multipliers per
  txid lane).  A probe matches only when both agree, so a false
  "present" needs a 128-bit collision (~2^64 birthday work even for an
  adversary minting both outputs) — the device verdict is trusted
  without consulting the host map.
* **Packed value store.**  Aligned with the keys: amount (two int32
  lanes), a 32-bit script hash (crc32 of the owning address), and the
  creation height.  Probes gather the amount lanes in the same
  dispatch, so the differential can cross-check resident amounts
  against SQL without extra traffic.
* **Windowed sorted probe.**  One ``searchsorted`` on the
  order-preserving high key lane, then an 8-slot window scan over the
  equal-run (key + check lanes compared elementwise).  int32 lanes
  throughout: without jax_enable_x64 JAX silently downcasts 64-bit
  arrays, which would truncate AFTER the host sort and hand
  searchsorted an unsorted array.  Sign-flip (``x ^ 0x8000_0000``)
  keeps uint32 order under int32 compare.
* **Shadow map, demoted.**  The exact multiset map ``fp64 ->
  [outpoints]`` is still maintained (it is the rollback/differential
  oracle and the twin resolver) but it is consulted ONLY when the
  device declares ambiguity: an equal-key run longer than the probe
  window, or a hit on a fingerprint that has ever had 64-bit twins.
  ``index.shadow_consults`` counts every consult; a collision-free
  block keeps it at zero (acceptance criterion).
* **O(delta) reorg.**  ``apply_block`` appends an undo record
  (created, spent, spent values) to a bounded log; ``rollback_block``
  replays the inverse as two sorted-slab splices — no full rebuild.
  Storage backends mirror this with per-outpoint delta add/remove in
  ``remove_blocks``.

All device work — probes, batched apply, the fused accept-path
dispatch (:func:`fused_probe`) — is issued through
``device/runtime.py``'s ``submit_call`` so the weighted fair scheduler
and degrade choke point govern it like every other kernel.
"""

from __future__ import annotations

import functools
import time
import zlib
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Outpoint = Tuple[str, int]

# Odd 64-bit mixing constant (2^64 / golden ratio).  The txid prefix is
# already uniform (it IS sha256 output); the multiply spreads the output
# index so (h, 0) and (h, 1) land far apart.
_MIX = 0x9E3779B97F4A7C15
_U64 = 0xFFFFFFFFFFFFFFFF

# Independent lane multipliers for the check fingerprint (xxhash64 /
# splitmix64 odd constants).  Any fixed distinct-odd-multiplier combine
# of sha256-uniform lanes is independent enough of the XOR fold that a
# simultaneous collision in both needs genuine 128-bit birthday work.
_CHECK_MULTS = (0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F,
                0x165667B19E3779F9, 0x27D4EB2F165667C5)
_MIX2 = 0xFF51AFD7ED558CCD

#: slots scanned past the searchsorted position; an equal-key run that
#: extends past the window flags the probe ambiguous (shadow consult)
PROBE_WINDOW = 8

_I32_MIN = np.int32(np.iinfo(np.int32).min)
_I32_MAX = np.int32(np.iinfo(np.int32).max)


def fingerprint(outpoint: Outpoint) -> int:
    """64-bit unsigned fingerprint of one outpoint: XOR-fold of the four
    u64 lanes of the (already sha256-uniform) txid, mixed with the
    output index.  Folding the WHOLE hash — not a prefix — keeps the
    fingerprint discriminating even for structured/test txids.

    Must stay bit-identical to ``fingerprint_batch`` — the class mixes
    both paths freely.
    """
    tx_hash, index = outpoint
    raw = bytes.fromhex(tx_hash)
    base = 0
    for off in range(0, 32, 8):
        base ^= int.from_bytes(raw[off:off + 8], "little")
    return (base ^ ((index + 1) * _MIX)) & _U64


def fingerprint_batch(outpoints: Sequence[Outpoint]) -> np.ndarray:
    """(N,) uint64 fingerprints in one ``np.frombuffer`` pass.

    One joined-hex decode + one frombuffer + vectorized fold/mix — no
    per-outpoint hashlib/int.from_bytes loop.
    """
    n = len(outpoints)
    if not n:
        return np.zeros(0, dtype=np.uint64)
    blob = bytes.fromhex("".join(o[0] for o in outpoints))
    lanes = np.frombuffer(blob, dtype="<u8").reshape(n, 4)
    base = np.bitwise_xor.reduce(lanes, axis=1)
    idx = np.fromiter((o[1] for o in outpoints), dtype=np.uint64, count=n)
    with np.errstate(over="ignore"):
        return base ^ ((idx + np.uint64(1)) * np.uint64(_MIX))


def check_fp(outpoint: Outpoint) -> int:
    """Scalar twin of :func:`check_batch` (tests / spot checks)."""
    tx_hash, index = outpoint
    raw = bytes.fromhex(tx_hash)
    acc = 0
    for k, off in enumerate(range(0, 32, 8)):
        lane = int.from_bytes(raw[off:off + 8], "little")
        acc ^= (lane * _CHECK_MULTS[k]) & _U64
    return (acc ^ (((index + 1) * _MIX2) & _U64)) & _U64


def check_batch(outpoints: Sequence[Outpoint]) -> np.ndarray:
    """(N,) uint64 *check* fingerprints — independent of
    :func:`fingerprint_batch`; together they form the 128-bit effective
    identity a resident probe trusts without host confirmation."""
    n = len(outpoints)
    if not n:
        return np.zeros(0, dtype=np.uint64)
    blob = bytes.fromhex("".join(o[0] for o in outpoints))
    lanes = np.frombuffer(blob, dtype="<u8").reshape(n, 4)
    idx = np.fromiter((o[1] for o in outpoints), dtype=np.uint64, count=n)
    with np.errstate(over="ignore"):
        acc = lanes[:, 0] * np.uint64(_CHECK_MULTS[0])
        for k in range(1, 4):
            acc = acc ^ (lanes[:, k] * np.uint64(_CHECK_MULTS[k]))
        return acc ^ ((idx + np.uint64(1)) * np.uint64(_MIX2))


def _lane_hi(fps: np.ndarray) -> np.ndarray:
    """High 32 bits as order-preserving int32 (sign-bit flip maps uint32
    order onto int32 order)."""
    hi = (fps >> np.uint64(32)).astype(np.uint32)
    return (hi ^ np.uint32(0x80000000)).view(np.int32)


def _lane_lo(fps: np.ndarray) -> np.ndarray:
    lo = (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return (lo ^ np.uint32(0x80000000)).view(np.int32)


def _eq_lanes(fps: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Equality-only int32 lane pair of a uint64 array (no order flip —
    the check lanes are compared, never sorted)."""
    u32 = fps.view(np.uint32).reshape(-1, 2)
    return (u32[:, 0].view(np.int32).copy(),
            u32[:, 1].view(np.int32).copy())


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length()) if n else 1


@functools.partial(jax.jit, static_argnames=("window",))
def _probe_kernel(keys_hi, keys_lo, chk_a, chk_b, amt_lo, amt_hi,
                  n_live, q_hi, q_lo, q_ca, q_cb, window):
    """Windowed sorted probe: searchsorted on the high key lane, then a
    ``window``-slot scan of the equal run comparing all four identity
    lanes.  Returns per query: full 128-bit hit, 64-bit key hit (the
    prefilter contract), run overflow (ambiguity), and the amount lanes
    gathered at the matched row."""
    cap = keys_hi.shape[0]
    pos = jnp.searchsorted(keys_hi, q_hi, side="left")
    idx = pos[:, None] + jnp.arange(window)[None, :]
    valid = idx < n_live
    idx_c = jnp.clip(idx, 0, cap - 1)
    hi_eq = (keys_hi[idx_c] == q_hi[:, None]) & valid
    key_eq = hi_eq & (keys_lo[idx_c] == q_lo[:, None])
    full_eq = key_eq & (chk_a[idx_c] == q_ca[:, None]) \
        & (chk_b[idx_c] == q_cb[:, None])
    hit = full_eq.any(axis=1)
    key_hit = key_eq.any(axis=1)
    overflow = hi_eq[:, window - 1]
    row = jnp.clip(pos + jnp.argmax(full_eq, axis=1), 0, cap - 1)
    return hit, key_hit, overflow, amt_lo[row], amt_hi[row]


class DeviceUtxoIndex:
    """HBM-resident sorted-fingerprint UTXO index, one per UTXO-class
    table: 128-bit effective identity, packed value store, bounded undo
    log, shadow map consulted only on declared ambiguity."""

    #: undo records retained for O(delta) reorg rollback; a reorg deeper
    #: than this falls back to the storage layer's rebuild
    UNDO_DEPTH = 64

    def __init__(self, outpoints: Iterable[Outpoint] = (),
                 values: Optional[Sequence[tuple]] = None):
        # shadow map: fp64 -> live outpoints with that fingerprint.  A
        # list, not a set: duplicates mirror the old multiset semantics
        # (add twice -> remove twice), and twins (distinct outpoints,
        # one fp64) stay individually tracked so spending one never
        # makes the survivor report absent — the one error class the
        # index must never produce.
        self._shadow: Dict[int, List[Outpoint]] = {}
        # fingerprints that EVER held >=2 live outpoints: any hit on one
        # routes to the shadow map (sticky — a surviving twin's row may
        # carry its spent sibling's check lanes after a k-th-duplicate
        # removal, so the ambiguity outlives the second entry)
        self._twin_fps: set = set()
        self._twins_arr: Optional[np.ndarray] = None
        self._host_keys = np.zeros(0, dtype=np.uint64)   # sorted fp64
        self._host_chk = np.zeros(0, dtype=np.uint64)    # aligned check
        self._host_amount = np.zeros(0, dtype=np.int64)  # aligned values
        self._host_script = np.zeros(0, dtype=np.uint32)
        self._host_height = np.zeros(0, dtype=np.uint32)
        self._dirty = True
        self._dev: Optional[tuple] = None                # device arrays
        self._undo: deque = deque(maxlen=self.UNDO_DEPTH)
        self._probes = 0
        self._shadow_consults = 0
        ops = [tuple(o) for o in outpoints]
        if ops:
            self.add(ops, values)

    def __len__(self):
        return int(self._host_keys.shape[0])

    # ------------------------------------------------------------ values --

    @staticmethod
    def _norm_values(n: int, values: Optional[Sequence[tuple]]):
        """(amount int64, script uint32, height uint32) arrays from the
        optional per-outpoint (amount, address|script_hash, height)
        tuples; zeros where unknown (membership never depends on them)."""
        amt = np.zeros(n, dtype=np.int64)
        script = np.zeros(n, dtype=np.uint32)
        height = np.zeros(n, dtype=np.uint32)
        if values is not None:
            for i, v in enumerate(values):
                if v is None:
                    continue
                a, s, h = (tuple(v) + (0, 0, 0))[:3]
                amt[i] = int(a or 0)
                if isinstance(s, str):
                    script[i] = zlib.crc32(s.encode())
                elif s:
                    script[i] = int(s) & 0xFFFFFFFF
                height[i] = int(h or 0) & 0xFFFFFFFF
        return amt, script, height

    def _capture_values(self, outpoints: Sequence[Outpoint]) -> List[tuple]:
        """Value rows for live outpoints (zeros when absent) — snapshot
        taken before a spend so the undo log can restore them."""
        out: List[tuple] = []
        if not outpoints:
            return out
        fps = fingerprint_batch(outpoints)
        chks = check_batch(outpoints)
        lo = np.searchsorted(self._host_keys, fps, side="left")
        hi = np.searchsorted(self._host_keys, fps, side="right")
        for i in range(len(outpoints)):
            row = None
            for j in range(int(lo[i]), int(hi[i])):
                if self._host_chk[j] == chks[i]:
                    row = j
                    break
            if row is None:
                out.append((0, 0, 0))
            else:
                out.append((int(self._host_amount[row]),
                            int(self._host_script[row]),
                            int(self._host_height[row])))
        return out

    # ------------------------------------------------------------ updates --

    def add(self, outpoints: Iterable[Outpoint],
            values: Optional[Sequence[tuple]] = None) -> None:
        ops = [tuple(o) for o in outpoints]
        if not ops:
            return
        fps = fingerprint_batch(ops)
        chks = check_batch(ops)
        for o, fp in zip(ops, fps.tolist()):
            bucket = self._shadow.setdefault(fp, [])
            bucket.append(o)
            if len(bucket) >= 2 and fp not in self._twin_fps:
                self._twin_fps.add(fp)
                self._twins_arr = None
        amt, script, height = self._norm_values(len(ops), values)
        # incremental sorted insert: sort only the (small) slab, then
        # splice it into place — no full re-sort of the whole key set
        order = np.argsort(fps, kind="stable")
        slab = fps[order]
        pos = np.searchsorted(self._host_keys, slab)
        self._host_keys = np.insert(self._host_keys, pos, slab)
        self._host_chk = np.insert(self._host_chk, pos, chks[order])
        self._host_amount = np.insert(self._host_amount, pos, amt[order])
        self._host_script = np.insert(self._host_script, pos, script[order])
        self._host_height = np.insert(self._host_height, pos, height[order])
        self._dirty = True

    def remove(self, outpoints: Iterable[Outpoint]) -> None:
        ops = [tuple(o) for o in outpoints]
        if not ops:
            return
        doomed: List[Tuple[int, int]] = []  # (fp, chk) of live removals
        fps = fingerprint_batch(ops)
        chks = check_batch(ops)
        for o, fp, chk in zip(ops, fps.tolist(), chks.tolist()):
            bucket = self._shadow.get(fp)
            if bucket is None or o not in bucket:
                # absent entries are a no-op, matching the SQL DELETE
                # (e.g. replaying a log whose spend references a
                # never-created output must report a MISMATCH, not crash)
                continue
            bucket.remove(o)
            if not bucket:
                del self._shadow[fp]
            doomed.append((fp, chk))
        if not doomed:
            return
        rem_fps = np.array([d[0] for d in doomed], dtype=np.uint64)
        lo = np.searchsorted(self._host_keys, rem_fps, side="left")
        hi = np.searchsorted(self._host_keys, rem_fps, side="right")
        marked: set = set()
        for (fp, chk), l, h in zip(doomed, lo.tolist(), hi.tolist()):
            # within the equal-fp run, delete the row whose check lanes
            # match (keeps twins' value rows individually correct); the
            # k-th-duplicate fallback covers true 128-bit twins, whose
            # rows are indistinguishable anyway
            pick = None
            for j in range(l, h):
                if j not in marked and self._host_chk[j] == chk:
                    pick = j
                    break
            if pick is None:
                for j in range(l, h):
                    if j not in marked:
                        pick = j
                        break
            if pick is not None:
                marked.add(pick)
        if not marked:
            return
        gone = np.fromiter(marked, dtype=np.int64, count=len(marked))
        self._host_keys = np.delete(self._host_keys, gone)
        self._host_chk = np.delete(self._host_chk, gone)
        self._host_amount = np.delete(self._host_amount, gone)
        self._host_script = np.delete(self._host_script, gone)
        self._host_height = np.delete(self._host_height, gone)
        self._dirty = True

    def apply_block(self, created: Sequence[Outpoint],
                    spent: Sequence[Outpoint],
                    created_values: Optional[Sequence[tuple]] = None,
                    materialize: bool = False) -> None:
        """Batched spend/create application for one accepted block,
        recorded in the undo log for :meth:`rollback_block`.
        ``materialize=True`` re-uploads the device arrays through the
        runtime now (one ``utxo_apply`` dispatch) instead of lazily on
        the next probe."""
        spent = [tuple(o) for o in spent]
        created = [tuple(o) for o in created]
        spent_values = self._capture_values(spent) if spent else []
        if spent:
            self.remove(spent)
        if created:
            self.add(created, created_values)
        self._undo.append((created, spent, spent_values))
        if materialize and (created or spent):
            self.materialize()

    def rollback_block(self) -> bool:
        """O(delta) inverse of the most recent :meth:`apply_block`:
        two sorted-slab splices, no rebuild.  False when the undo log
        is exhausted (caller falls back to a rebuild)."""
        if not self._undo:
            return False
        created, spent, spent_values = self._undo.pop()
        if created:
            self.remove(created)
        if spent:
            self.add(spent, spent_values)
        return True

    def undo_depth(self) -> int:
        return len(self._undo)

    # ------------------------------------------------------ device state --

    def _device_state(self) -> tuple:
        """(keys_hi, keys_lo, chk_a, chk_b, amt_lo, amt_hi, n_live) jnp
        arrays at power-of-two capacity.  Must only run on the runtime's
        drainer thread (inside a submitted call)."""
        if self._dirty or self._dev is None:
            n = len(self._host_keys)
            cap = _pow2(n)
            pad = cap - n

            def _padded(lane: np.ndarray, fill) -> np.ndarray:
                return np.concatenate(
                    [lane, np.full(pad, fill, dtype=np.int32)])

            chk_a, chk_b = _eq_lanes(self._host_chk)
            amt_u = self._host_amount.view(np.uint64)
            amt_lo = (amt_u & np.uint64(0xFFFFFFFF)).astype(
                np.uint32).view(np.int32)
            amt_hi = (amt_u >> np.uint64(32)).astype(
                np.uint32).view(np.int32)
            self._dev = tuple(jnp.asarray(_padded(lane, fill)) for lane, fill in (
                (_lane_hi(self._host_keys), _I32_MAX),
                (_lane_lo(self._host_keys), _I32_MAX),
                (chk_a, 0), (chk_b, 0),
                (amt_lo, 0), (amt_hi, 0),
            )) + (np.int32(n),)
            self._dirty = False
        return self._dev

    def materialize(self) -> None:
        """Upload the current host state to the device through the
        runtime (kernel ``utxo_apply``) — the batched spend/create
        transfer the accept path schedules after each block."""
        from ..device.runtime import get_runtime
        from ..telemetry import device as ktel

        n = len(self._host_keys)

        def _upload():
            t0 = time.perf_counter()
            dev = self._device_state()
            jax.block_until_ready(dev[0])
            ktel.record_batch("utxo_apply", real=n,
                              padded=int(dev[0].shape[0]),
                              seconds=time.perf_counter() - t0,
                              compile_key=int(dev[0].shape[0]))
            return True

        get_runtime().submit_call(_upload, kernel="utxo_apply",
                                  source="index").result()

    def resident_bytes(self) -> int:
        """Device residency: six int32 lanes at padded capacity."""
        return 6 * 4 * _pow2(len(self._host_keys))

    def stats(self) -> dict:
        return {
            "entries": len(self),
            "capacity": _pow2(len(self._host_keys)),
            "resident_bytes": self.resident_bytes(),
            "probes": self._probes,
            "shadow_consults": self._shadow_consults,
            "twin_fingerprints": len(self._twin_fps),
            "undo_depth": len(self._undo),
        }

    # ------------------------------------------------------------ queries --

    def _twins_sorted(self) -> np.ndarray:
        if self._twins_arr is None:
            self._twins_arr = np.array(
                sorted(self._twin_fps), dtype=np.uint64)
        return self._twins_arr

    def _probe_eval(self, ops: Sequence[Outpoint], fps: np.ndarray,
                    chks: np.ndarray) -> tuple:
        """Run one probe kernel + host postprocess.  Must run on the
        runtime drainer thread (inside a submitted call).  Returns
        (present bool[N], maybe bool[N], amounts int64[N],
        shadow_consults)."""
        from ..telemetry import device as ktel

        n = len(ops)
        qn = _pow2(n)
        t0 = time.perf_counter()
        dev = self._device_state()

        def _padq(lane: np.ndarray, fill) -> np.ndarray:
            return np.concatenate(
                [lane, np.full(qn - n, fill, dtype=np.int32)])

        q_ca, q_cb = _eq_lanes(chks)
        hit, key_hit, overflow, amt_lo, amt_hi = _probe_kernel(
            *dev[:6], dev[6],
            jnp.asarray(_padq(_lane_hi(fps), _I32_MIN)),
            jnp.asarray(_padq(_lane_lo(fps), _I32_MIN)),
            jnp.asarray(_padq(q_ca, 0)), jnp.asarray(_padq(q_cb, 0)),
            window=PROBE_WINDOW)
        hit = np.asarray(hit)[:n]
        key_hit = np.asarray(key_hit)[:n]
        overflow = np.asarray(overflow)[:n]
        amt_lo = np.asarray(amt_lo)[:n]
        amt_hi = np.asarray(amt_hi)[:n]
        dt = time.perf_counter() - t0

        ambiguous = overflow.copy()
        twins = self._twins_sorted()
        if twins.size:
            ambiguous |= (key_hit & np.isin(fps, twins))
        present = hit & ~ambiguous
        consults = 0
        for i in np.nonzero(ambiguous)[0]:
            bucket = self._shadow.get(int(fps[i]))
            present[i] = bucket is not None and tuple(ops[i]) in bucket
            consults += 1
        amounts = ((amt_hi.view(np.uint32).astype(np.uint64)
                    << np.uint64(32))
                   | amt_lo.view(np.uint32).astype(np.uint64)
                   ).view(np.int64)
        amounts = np.where(present & ~ambiguous, amounts, 0)
        maybe = key_hit | overflow
        self._probes += 1
        self._shadow_consults += consults
        ktel.record_batch("utxo_probe", real=n, padded=qn, seconds=dt,
                          compile_key=(int(dev[0].shape[0]), qn))
        ktel.record_index_probe(n, consults, int(ambiguous.sum()))
        return present, maybe, amounts, consults

    def _probe(self, outpoints: Sequence[Outpoint]) -> tuple:
        """One standalone probe dispatch through the runtime."""
        ops = [tuple(o) for o in outpoints]
        fps = fingerprint_batch(ops)
        chks = check_batch(ops)
        from ..device.runtime import get_runtime

        return get_runtime().submit_call(
            lambda: self._probe_eval(ops, fps, chks),
            kernel="utxo_probe", source="index").result()

    def maybe_contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool prefilter contract: False is definitive absence;
        True means a fingerprint hit (use ``contains_batch`` for the
        exact answer)."""
        if not outpoints:
            return np.zeros(0, dtype=bool)
        return self._probe(outpoints)[1]

    def contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool EXACT membership in one device dispatch.

        The 128-bit lane compare answers directly; the shadow map is
        consulted only for probes the kernel itself declares ambiguous
        (run overflow or a known-twin fingerprint)."""
        if not outpoints:
            return np.zeros(0, dtype=bool)
        return self._probe(outpoints)[0]

    def lookup_batch(self, outpoints: Sequence[Outpoint]) -> tuple:
        """(present bool[N], amounts int64[N]) — membership plus the
        resident value store's amount column, one dispatch."""
        if not outpoints:
            return np.zeros(0, dtype=bool), np.zeros(0, dtype=np.int64)
        present, _maybe, amounts, _c = self._probe(outpoints)
        return present, amounts

    def shadow_contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool membership answered PURELY by the host shadow map —
        the byte-identity differential's oracle; never dispatches."""
        out = np.zeros(len(outpoints), dtype=bool)
        if not len(outpoints):
            return out
        ops = [tuple(o) for o in outpoints]
        for i, (o, fp) in enumerate(
                zip(ops, fingerprint_batch(ops).tolist())):
            bucket = self._shadow.get(fp)
            out[i] = bucket is not None and o in bucket
        return out

    def missing(self, outpoints: Sequence[Outpoint]) -> List[Outpoint]:
        """Outpoints that are definitely absent (exact)."""
        present = self.contains_batch(outpoints)
        return [o for o, m in zip(outpoints, present) if not m]


def fused_probe(parts: Sequence[Tuple[DeviceUtxoIndex, Sequence[Outpoint]]],
                extra_fn: Optional[Callable] = None,
                source: str = "block") -> tuple:
    """ONE runtime dispatch covering every (index, outpoints) part —
    the accept path's fused membership probe.  ``extra_fn`` (e.g. the
    device txid batch for the same micro-batch) runs inside the same
    submitted call, so digest prep and outpoint probing share a single
    scheduler slot instead of racing each other through the queue.

    Returns ``([(present, amounts, shadow_consults), ...], extra)``
    with parts in input order.
    """
    staged = []
    for index, outpoints in parts:
        ops = [tuple(o) for o in outpoints]
        staged.append((index, ops, fingerprint_batch(ops), check_batch(ops)))

    def _run():
        results = []
        for index, ops, fps, chks in staged:
            if not ops:
                results.append((np.zeros(0, dtype=bool),
                                np.zeros(0, dtype=np.int64), 0))
                continue
            present, _maybe, amounts, consults = index._probe_eval(
                ops, fps, chks)
            results.append((present, amounts, consults))
        extra = extra_fn() if extra_fn is not None else None
        return results, extra

    from ..device.runtime import get_runtime

    return get_runtime().submit_call(
        _run, kernel="accept_fused", source=source).result()
