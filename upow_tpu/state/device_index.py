"""Device-resident UTXO membership prefilter (SURVEY.md §2.2).

The block-accept hot path tests every input outpoint against the unspent
set (reference manager.py:531-615 does per-class SQL set-diffs).  Here
outpoints are fingerprinted to 32 bits (first 4 bytes of
sha256(tx_hash || index)), kept as ONE sorted int32 array in HBM, and a
whole block's inputs are tested with a single ``searchsorted`` + gather
compare.  (int32, not int64: without jax_enable_x64 JAX silently
downcasts 64-bit arrays, which would truncate AFTER the host sort and
hand searchsorted an unsorted array.)

The fingerprint is a *prefilter*, not the consensus decision:

* fingerprint miss -> outpoint is definitely NOT unspent (exact), so
  double-spend floods and bad forks reject after one device call;
* fingerprint hit  -> "maybe" — the caller escalates to storage
  (``ChainState.outpoints_exist`` confirms hits with its batched SQL).

Holding only 4 bytes per outpoint host+device-side, the index scales to
many millions of UTXOs.  Trusting hits outright would be unsound — a
32-bit collision (trivially grindable, and ~0.02%/query by chance at
1M UTXOs) must cost one SQL confirm, never a wrong verdict — hence the
escalation, exactly the SURVEY §2.2 design.
"""

from __future__ import annotations

import hashlib
from collections import Counter
from typing import Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Outpoint = Tuple[str, int]


def fingerprint(outpoint: Outpoint) -> int:
    tx_hash, index = outpoint
    digest = hashlib.sha256(
        bytes.fromhex(tx_hash) + index.to_bytes(2, "little")).digest()
    return int.from_bytes(digest[:4], "little", signed=True)  # int32


@jax.jit
def _member_mask(sorted_keys, queries):
    pos = jnp.searchsorted(sorted_keys, queries)
    pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    return sorted_keys[pos] == queries


class DeviceUtxoIndex:
    """Sorted-fingerprint membership prefilter, one per UTXO-class table."""

    def __init__(self, outpoints: Iterable[Outpoint] = ()):
        # MULTISET of fingerprints: two live outpoints may share one
        # (expected ~n²/2³³ pairs — ~100 at 1M UTXOs).  A plain set would
        # over-remove when one twin is spent, and a wrong "definitely
        # absent" on the survivor would REJECT a valid block — the one
        # error class a prefilter must never produce.
        self._fps = Counter(fingerprint(o) for o in outpoints)
        self._dirty = True
        self._keys = None

    def __len__(self):
        return sum(self._fps.values())

    def add(self, outpoints: Iterable[Outpoint]) -> None:
        self._fps.update(fingerprint(o) for o in outpoints)
        self._dirty = True

    def remove(self, outpoints: Iterable[Outpoint]) -> None:
        for o in outpoints:
            fp = fingerprint(o)
            left = self._fps[fp] - 1
            if left > 0:
                self._fps[fp] = left
            elif fp in self._fps:
                del self._fps[fp]
            # absent entries are a no-op, matching the SQL DELETE and the
            # old set semantics (e.g. replaying a log whose spend
            # references a never-created output must report a MISMATCH,
            # not crash)
        self._dirty = True

    def _device_keys(self):
        if self._dirty:
            keys = np.fromiter(self._fps.keys(), dtype=np.int32,
                               count=len(self._fps))
            keys.sort()
            # pad to a non-empty power-of-two length to bound recompiles
            n = max(1, 1 << (len(keys) - 1).bit_length()) if len(keys) else 1
            pad = np.full(n - len(keys), np.iinfo(np.int32).max, dtype=np.int32)
            self._keys = jnp.asarray(np.concatenate([keys, pad]))
            self._dirty = False
        return self._keys

    def maybe_contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool: False is definitive absence; True means escalate."""
        if not outpoints:
            return np.zeros(0, dtype=bool)
        queries = np.fromiter(
            (fingerprint(o) for o in outpoints), dtype=np.int32,
            count=len(outpoints),
        )
        n = 1 << (len(queries) - 1).bit_length() if len(queries) else 1
        padded = np.concatenate([
            queries, np.full(n - len(queries), np.iinfo(np.int32).min, np.int32)])
        return np.asarray(
            _member_mask(self._device_keys(), jnp.asarray(padded))
        )[: len(outpoints)]

    def missing(self, outpoints: Sequence[Outpoint]) -> List[Outpoint]:
        """Outpoints that are definitely absent (no escalation needed)."""
        maybe = self.maybe_contains_batch(outpoints)
        return [o for o, m in zip(outpoints, maybe) if not m]
