"""Device-resident UTXO membership prefilter (SURVEY.md §2.2).

The block-accept hot path tests every input outpoint against the unspent
set (reference manager.py:531-615 does per-class SQL set-diffs).  Here
outpoints are fingerprinted to 64 bits (first 8 bytes of
sha256(tx_hash || index)), kept as ONE sorted int64 array in HBM, and a
whole block's inputs are tested with a single ``searchsorted`` + gather
compare.

The fingerprint is a *prefilter*, not the consensus decision:

* fingerprint miss -> outpoint is definitely NOT unspent (exact), so
  double-spend floods and bad forks reject after one device call;
* fingerprint hit  -> "maybe" — the caller escalates to storage
  (``ChainState.outpoints_exist`` confirms hits with its batched SQL).

Holding only 8 bytes per outpoint host+device-side, the index scales to
many millions of UTXOs.  Trusting hits outright would be unsound: an
attacker who grinds ~2^44 hashes finds an outpoint colliding with some
existing fingerprint, and a false "unspent" verdict is a consensus
break — hence the escalation, exactly the SURVEY §2.2 design.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Outpoint = Tuple[str, int]


def fingerprint(outpoint: Outpoint) -> int:
    tx_hash, index = outpoint
    digest = hashlib.sha256(
        bytes.fromhex(tx_hash) + index.to_bytes(2, "little")).digest()
    return int.from_bytes(digest[:8], "little", signed=True)  # int64


@jax.jit
def _member_mask(sorted_keys, queries):
    pos = jnp.searchsorted(sorted_keys, queries)
    pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    return sorted_keys[pos] == queries


class DeviceUtxoIndex:
    """Sorted-fingerprint membership prefilter, one per UTXO-class table."""

    def __init__(self, outpoints: Iterable[Outpoint] = ()):
        self._fps = {fingerprint(o) for o in outpoints}
        self._dirty = True
        self._keys = None

    def __len__(self):
        return len(self._fps)

    def add(self, outpoints: Iterable[Outpoint]) -> None:
        self._fps.update(fingerprint(o) for o in outpoints)
        self._dirty = True

    def remove(self, outpoints: Iterable[Outpoint]) -> None:
        # NB: a (vanishingly rare) colliding pair would be over-removed;
        # the escalation to storage keeps that sound — it only costs a
        # false "maybe-not" turned into a definite miss for the twin.
        self._fps.difference_update(fingerprint(o) for o in outpoints)
        self._dirty = True

    def _device_keys(self):
        if self._dirty:
            keys = np.fromiter(iter(self._fps), dtype=np.int64,
                               count=len(self._fps))
            keys.sort()
            # pad to a non-empty power-of-two length to bound recompiles
            n = max(1, 1 << (len(keys) - 1).bit_length()) if len(keys) else 1
            pad = np.full(n - len(keys), np.iinfo(np.int64).max, dtype=np.int64)
            self._keys = jnp.asarray(np.concatenate([keys, pad]))
            self._dirty = False
        return self._keys

    def maybe_contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool: False is definitive absence; True means escalate."""
        if not outpoints:
            return np.zeros(0, dtype=bool)
        queries = np.fromiter(
            (fingerprint(o) for o in outpoints), dtype=np.int64,
            count=len(outpoints),
        )
        n = 1 << (len(queries) - 1).bit_length() if len(queries) else 1
        padded = np.concatenate([
            queries, np.full(n - len(queries), np.iinfo(np.int64).min, np.int64)])
        return np.asarray(
            _member_mask(self._device_keys(), jnp.asarray(padded))
        )[: len(outpoints)]

    def missing(self, outpoints: Sequence[Outpoint]) -> List[Outpoint]:
        """Outpoints that are definitely absent (no escalation needed)."""
        maybe = self.maybe_contains_batch(outpoints)
        return [o for o, m in zip(outpoints, maybe) if not m]
