"""Device-resident exact UTXO outpoint index (SURVEY.md §2.2, ISSUE 7).

The block-accept hot path tests every input outpoint against the unspent
set (reference manager.py:531-615 does per-class SQL set-diffs).  Earlier
rounds kept a 32-bit *prefilter* here and escalated every hit to batched
SQL.  This round promotes it to an **exact** index:

* 64-bit fingerprint per outpoint — the first 8 bytes of the (already
  uniformly distributed) txid, mixed with the output index.  Computed for
  whole batches in ONE ``np.frombuffer`` pass over the joined hash
  prefixes instead of a Python-level hashlib loop per outpoint.
* a host-side exact map ``fp64 -> [outpoints]`` that resolves the
  astronomically-rare (but adversarially grindable, and therefore
  handled) 64-bit twins, so membership answers are EXACT — the SQL
  escalation that used to confirm every prefilter hit is gone from the
  hot path.
* a sorted host ``uint64`` key array maintained by incremental
  ``searchsorted`` + ``insert``/``delete`` — block accept appends a
  sorted slab into place instead of re-sorting the whole set.
* an HBM-resident int32 shadow of the high 32 fingerprint bits (order
  preserved by flipping the sign bit: ``(hi ^ 0x8000_0000)`` viewed as
  int32) for the one-dispatch ``searchsorted`` prefilter.  int32, not
  int64: without jax_enable_x64 JAX silently downcasts 64-bit arrays,
  which would truncate AFTER the host sort and hand searchsorted an
  unsorted array.

``contains_batch`` is the exact membership test (device prefilter to
reject definite misses in one dispatch, host map to confirm the hits).
``maybe_contains_batch`` keeps the historical prefilter contract (False
is definitive absence; True means "maybe") for callers that only want
the cheap device-side reject.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Outpoint = Tuple[str, int]

# Odd 64-bit mixing constant (2^64 / golden ratio).  The txid prefix is
# already uniform (it IS sha256 output); the multiply spreads the output
# index so (h, 0) and (h, 1) land far apart.
_MIX = 0x9E3779B97F4A7C15
_U64 = 0xFFFFFFFFFFFFFFFF


def fingerprint(outpoint: Outpoint) -> int:
    """64-bit unsigned fingerprint of one outpoint: XOR-fold of the four
    u64 lanes of the (already sha256-uniform) txid, mixed with the
    output index.  Folding the WHOLE hash — not a prefix — keeps the
    fingerprint discriminating even for structured/test txids; grinding
    a collision still costs sha256 birthday work (~2^32), and the exact
    map makes collisions a perf footnote, never a wrong verdict.

    Must stay bit-identical to ``fingerprint_batch`` — the class mixes
    both paths freely.
    """
    tx_hash, index = outpoint
    raw = bytes.fromhex(tx_hash)
    base = 0
    for off in range(0, 32, 8):
        base ^= int.from_bytes(raw[off:off + 8], "little")
    return (base ^ ((index + 1) * _MIX)) & _U64


def fingerprint_batch(outpoints: Sequence[Outpoint]) -> np.ndarray:
    """(N,) uint64 fingerprints in one ``np.frombuffer`` pass.

    One joined-hex decode + one frombuffer + vectorized fold/mix — no
    per-outpoint hashlib/int.from_bytes loop (satellite: measurable
    per-block host win on 8k-input blocks).
    """
    n = len(outpoints)
    if not n:
        return np.zeros(0, dtype=np.uint64)
    blob = bytes.fromhex("".join(o[0] for o in outpoints))
    lanes = np.frombuffer(blob, dtype="<u8").reshape(n, 4)
    base = np.bitwise_xor.reduce(lanes, axis=1)
    idx = np.fromiter((o[1] for o in outpoints), dtype=np.uint64, count=n)
    with np.errstate(over="ignore"):
        return base ^ ((idx + np.uint64(1)) * np.uint64(_MIX))


@jax.jit
def _member_mask(sorted_keys, queries):
    pos = jnp.searchsorted(sorted_keys, queries)
    pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    return sorted_keys[pos] == queries


def _hi32_i32(fps: np.ndarray) -> np.ndarray:
    """High 32 fingerprint bits as order-preserving int32 (sign-bit flip
    maps uint32 order onto int32 order)."""
    hi = (fps >> np.uint64(32)).astype(np.uint32)
    return (hi ^ np.uint32(0x80000000)).view(np.int32)


class DeviceUtxoIndex:
    """Exact sorted-fingerprint outpoint index, one per UTXO-class table."""

    def __init__(self, outpoints: Iterable[Outpoint] = ()):
        ops = [tuple(o) for o in outpoints]
        fps = fingerprint_batch(ops)
        # exact map: fp64 -> live outpoints with that fingerprint.  A
        # list, not a set: duplicates mirror the old multiset semantics
        # (add twice -> remove twice), and twins (distinct outpoints, one
        # fp64) stay individually tracked so spending one never makes the
        # survivor report absent — the one error class the index must
        # never produce.
        self._exact: Dict[int, List[Outpoint]] = {}
        for o, fp in zip(ops, fps.tolist()):
            self._exact.setdefault(fp, []).append(o)
        keys = fps.copy()
        keys.sort()
        self._host_keys = keys          # sorted uint64, one entry per live op
        self._dirty = True
        self._keys = None               # device int32 shadow (lazy)

    def __len__(self):
        return int(self._host_keys.shape[0])

    # ------------------------------------------------------------ updates --

    def add(self, outpoints: Iterable[Outpoint]) -> None:
        ops = [tuple(o) for o in outpoints]
        if not ops:
            return
        fps = fingerprint_batch(ops)
        for o, fp in zip(ops, fps.tolist()):
            self._exact.setdefault(fp, []).append(o)
        # incremental sorted insert: sort only the (small) slab, then
        # splice it into place — no full re-sort of the whole key set
        slab = np.sort(fps)
        pos = np.searchsorted(self._host_keys, slab)
        self._host_keys = np.insert(self._host_keys, pos, slab)
        self._dirty = True

    def remove(self, outpoints: Iterable[Outpoint]) -> None:
        ops = [tuple(o) for o in outpoints]
        if not ops:
            return
        removed: List[int] = []
        for o, fp in zip(ops, fingerprint_batch(ops).tolist()):
            bucket = self._exact.get(fp)
            if bucket is None or o not in bucket:
                # absent entries are a no-op, matching the SQL DELETE
                # (e.g. replaying a log whose spend references a
                # never-created output must report a MISMATCH, not crash)
                continue
            bucket.remove(o)
            if not bucket:
                del self._exact[fp]
            removed.append(fp)
        if not removed:
            return
        rem = np.sort(np.array(removed, dtype=np.uint64))
        pos = np.searchsorted(self._host_keys, rem, side="left")
        # k-th duplicate of an equal fp deletes the k-th occurrence
        off = np.arange(len(rem)) - np.searchsorted(rem, rem, side="left")
        self._host_keys = np.delete(self._host_keys, pos + off)
        self._dirty = True

    def apply_block(self, created: Sequence[Outpoint],
                    spent: Sequence[Outpoint]) -> None:
        """Batched spend/create application for one accepted (or
        rolled-back, with the roles swapped) block."""
        if spent:
            self.remove(spent)
        if created:
            self.add(created)

    # ------------------------------------------------------------ queries --

    def _device_keys(self):
        if self._dirty:
            keys = _hi32_i32(self._host_keys)
            # drop twin duplicates device-side (mask only needs presence)
            # and pad to a non-empty power-of-two to bound recompiles
            keys = np.unique(keys)
            n = max(1, 1 << (len(keys) - 1).bit_length()) if len(keys) else 1
            pad = np.full(n - len(keys), np.iinfo(np.int32).max, dtype=np.int32)
            self._keys = jnp.asarray(np.concatenate([keys, pad]))
            self._dirty = False
        return self._keys

    def _prefilter(self, fps: np.ndarray) -> np.ndarray:
        queries = _hi32_i32(fps)
        n = 1 << (len(queries) - 1).bit_length() if len(queries) else 1
        padded = np.concatenate([
            queries,
            np.full(n - len(queries), np.iinfo(np.int32).min, np.int32)])
        # the searchsorted dispatch goes through the device owner so
        # index lookups interleave (weight: index=3) with miner/verify
        # batches instead of racing them for the chip
        from ..device.runtime import get_runtime

        mask = get_runtime().submit_call(
            lambda: np.asarray(
                _member_mask(self._device_keys(), jnp.asarray(padded))),
            kernel="utxo_index", source="index").result()
        return mask[: len(fps)]

    def maybe_contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool prefilter: False is definitive absence; True means
        a high-32-bit fingerprint hit (use ``contains_batch`` for the
        exact answer)."""
        if not outpoints:
            return np.zeros(0, dtype=bool)
        return self._prefilter(fingerprint_batch(outpoints))

    def contains_batch(self, outpoints: Sequence[Outpoint]) -> np.ndarray:
        """(N,) bool EXACT membership — no SQL escalation needed.

        One device ``searchsorted`` dispatch rejects definite misses;
        the host exact map confirms each surviving hit (including
        resolving fp64 twins down to the precise outpoint).
        """
        if not outpoints:
            return np.zeros(0, dtype=bool)
        ops = [tuple(o) for o in outpoints]
        fps = fingerprint_batch(ops)
        maybe = self._prefilter(fps)
        out = np.zeros(len(ops), dtype=bool)
        fp_list = fps.tolist()
        for i in np.nonzero(maybe)[0]:
            bucket = self._exact.get(fp_list[i])
            out[i] = bucket is not None and ops[i] in bucket
        return out

    def missing(self, outpoints: Sequence[Outpoint]) -> List[Outpoint]:
        """Outpoints that are definitely absent (exact)."""
        present = self.contains_batch(outpoints)
        return [o for o, m in zip(outpoints, present) if not m]
