"""Chain state: sqlite-backed storage + device-resident UTXO index."""

from .storage import ChainState
from .device_index import DeviceUtxoIndex
