"""Generation-anchored hot-state read cache (docs/CACHING.md).

Chain state is immutable between writes: between two block accepts (or
pending-journal changes) every read-endpoint answer is a pure function
of ``(tip_block_hash, pending_journal_seq)``.  This cache keys every
entry by an integer *epoch* that stands in for that tuple: the node
bumps the epoch synchronously after each committed write (block accept,
reorg, pending add/remove — the ``BlockManager.on_pending_removed``
hook pattern generalized to ``on_state_committed`` and
``ChainState.on_blocks_removed``), so invalidation is O(1) and precise.
A cached entry is served only on an exact epoch match, which makes
responses byte-identical to the uncached path *by construction* — no
TTL guessing, no staleness window from the writer's own perspective.

Multi-worker deployments share state through SQL, where another
worker's write bumps nothing in this process.  For that, the epoch is
re-anchored at most every ``revalidate_interval`` seconds against the
real validator tuple ``(tip hash, pending_journal_stamp())`` — the same
journal-stamp reconciliation the mempool already uses — and any
observed change bumps the epoch (``foreign_bumps``).  Interval 0 means
revalidate on every read (used by tests and correct-but-slow shared-DB
setups); a negative interval disables foreign revalidation entirely
(sole-writer processes, e.g. the swarm simulator and benches).

What is cached is the *encoded response body* (bytes), not the Python
object: the handler's dumps function runs once per (entry, generation)
and the stored bytes are fanned out verbatim, so a cache hit costs a
dict lookup instead of SQL + JSON encoding.

Entries are grouped into classes (``address``, ``blocks``, ``tx``,
``supply``, ...) each with its own LRU byte cap, so one scan of cold
block history cannot evict the hot wallet set.  Concurrent misses for
the same ``(class, key, epoch)`` coalesce through a singleflight table:
one producer runs, everyone else awaits its future
(``singleflight_coalesced``).
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Awaitable, Callable, Dict, Optional, Tuple

__all__ = ["HotStateCache"]


class _ClassCache:
    """One LRU byte-capped entry class."""

    __slots__ = ("entries", "bytes", "cap")

    def __init__(self, cap: int):
        # key -> (epoch, body)
        self.entries: "OrderedDict[tuple, Tuple[int, bytes]]" = OrderedDict()
        self.bytes = 0
        self.cap = cap


class HotStateCache:
    def __init__(self, state, config=None):
        from ..config import CacheConfig

        self.state = state
        self.config = config or CacheConfig()
        self.enabled = bool(self.config.enabled)
        self._classes: Dict[str, _ClassCache] = {}
        self._class_caps = self.config.parsed_class_caps()
        self._inflight: Dict[tuple, asyncio.Future] = {}
        self._epoch = 0
        self._epoch_changed_at = time.monotonic()
        # validator tuple observed at the last foreign revalidation;
        # None right after a local bump (re-anchored lazily)
        self._anchor: Optional[tuple] = None
        self._last_revalidate = float("-inf")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.singleflight_coalesced = 0
        self.bumps = 0
        self.foreign_bumps = 0

    # ------------------------------------------------------- generation ---
    def bump(self, reason: str = "") -> None:
        """Advance the generation after a local committed write.  O(1):
        entries are not scanned or dropped here — stale ones simply stop
        matching and age out through the LRU."""
        self._epoch += 1
        self._anchor = None  # re-anchor lazily on the next revalidation
        self._epoch_changed_at = time.monotonic()
        self.bumps += 1

    async def generation(self) -> int:
        """Current epoch, re-anchored against the shared database when
        the revalidation interval says it is due."""
        interval = self.config.revalidate_interval
        if interval < 0:
            return self._epoch
        now = time.monotonic()
        if now - self._last_revalidate < interval:
            return self._epoch
        # claim the slot before awaiting so concurrent readers don't
        # pile duplicate anchor reads (single-threaded up to this point)
        self._last_revalidate = now
        epoch0 = self._epoch
        anchor = await self._read_anchor()
        if self._epoch != epoch0:
            # a local bump landed mid-read; its invalidation supersedes
            # whatever snapshot this anchor read saw
            return self._epoch
        if self._anchor is None:
            self._anchor = anchor
        elif anchor != self._anchor:
            self._anchor = anchor
            self._epoch += 1
            self._epoch_changed_at = time.monotonic()
            self.foreign_bumps += 1
        return self._epoch

    async def _read_anchor(self) -> tuple:
        last = await self.state.get_last_block()
        stamp = await self.state.pending_journal_stamp()
        return ((last or {}).get("hash"), tuple(stamp))

    # ------------------------------------------------------------ reads ---
    async def get_bytes(self, entry_class: str, key: tuple,
                        produce: Callable[[], Awaitable[bytes]]) -> bytes:
        """Read-through: serve ``(entry_class, key)`` at the current
        generation, calling ``produce()`` (which must return the encoded
        body bytes) on a miss.  Concurrent misses for the same key and
        generation share one ``produce()`` call."""
        gen = await self.generation()
        cc = self._class(entry_class)
        hit = cc.entries.get(key)
        if hit is not None and hit[0] == gen:
            self.hits += 1
            cc.entries.move_to_end(key)
            return hit[1]
        self.misses += 1
        flight_key = (entry_class, key, gen)
        fut = self._inflight.get(flight_key)
        if fut is not None:
            self.singleflight_coalesced += 1
            return await asyncio.shield(fut)
        fut = asyncio.get_event_loop().create_future()
        # retrieve the outcome even if no follower ever awaits it
        fut.add_done_callback(lambda f: f.cancelled() or f.exception())
        self._inflight[flight_key] = fut
        try:
            body = await produce()
        except BaseException as e:
            if not fut.done():
                if isinstance(e, asyncio.CancelledError):
                    fut.cancel()
                else:
                    fut.set_exception(e)
            raise
        else:
            if not fut.done():
                fut.set_result(body)
            self._store(cc, key, gen, body)
            return body
        finally:
            self._inflight.pop(flight_key, None)

    def _class(self, name: str) -> _ClassCache:
        cc = self._classes.get(name)
        if cc is None:
            cap = self._class_caps.get(name, self.config.class_cap_bytes)
            cc = self._classes[name] = _ClassCache(cap)
        return cc

    def _store(self, cc: _ClassCache, key: tuple, gen: int,
               body: bytes) -> None:
        size = len(body)
        if size > min(cc.cap, self.config.max_entry_bytes):
            return  # would evict the whole class for one oversized body
        old = cc.entries.pop(key, None)
        if old is not None:
            cc.bytes -= len(old[1])
        cc.entries[key] = (gen, body)
        cc.bytes += size
        while cc.bytes > cc.cap and cc.entries:
            _, (_, evicted) = cc.entries.popitem(last=False)
            cc.bytes -= len(evicted)
            self.evictions += 1

    # ------------------------------------------------------------ stats ---
    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "enabled": self.enabled,
            "generation": self._epoch,
            "generation_age_seconds": round(
                time.monotonic() - self._epoch_changed_at, 3),
            "hits": self.hits,
            "misses": self.misses,
            "hit_ratio": round(self.hits / total, 4) if total else 0.0,
            "evictions": self.evictions,
            "singleflight_coalesced": self.singleflight_coalesced,
            "bumps": self.bumps,
            "foreign_bumps": self.foreign_bumps,
            "classes": {
                name: {"entries": len(cc.entries), "bytes": cc.bytes,
                       "cap_bytes": cc.cap}
                for name, cc in sorted(self._classes.items())
            },
        }
