"""PostgreSQL chain-state backend — drop-in interop with a reference DB.

Implements the same storage seam as :class:`upow_tpu.state.storage.ChainState`
(the consensus views are shared via :class:`upow_tpu.state.views.StateViews`)
against the reference's EXACT schema (``/root/reference/schema.sql``,
``database.py:33-91``): an operator can point this node at an existing
uPow PostgreSQL database — or create a fresh one with
:meth:`PgChainState.ensure_schema` — and reuse the reference ecosystem's
tooling (db_setup.sh, makefile.postgres, create_unspent_outputs.py).

Representation differences vs the sqlite backend, all absorbed here so
the rest of the framework sees one API (int smallest-units, epoch ints):

* output tables carry NO amount column — amounts resolve through
  ``transactions.outputs_amounts`` (schema.sql:12-20), so every
  amount-bearing read is a JOIN with the array indexed host-side,
* ``fees``/``reward`` are NUMERIC(14,6) **coins** (quantized to 6 dp by
  the column type — a reference-inherited representation limit; the
  consensus-critical fee path recomputes from tx amounts and never
  round-trips through these columns),
* ``timestamp``/``propagation_time`` are TIMESTAMP(0) (naive UTC),
* address arrays are TEXT[] (the sqlite backend stores JSON),
* the outpoint index column is ``"index"`` (quoted — reserved-adjacent).

The driver seam (state/pgdriver.py) keeps the SQL here runnable both on
asyncpg (production) and on the sqlite-backed mock (CI without a
server); see that module for the SQL-subset discipline.  The async
storage methods await the driver's awaitable facade, so database round
trips never block the node's event loop (the reference's asyncpg usage
is async-native the same way); only CLI tooling uses the blocking
facade.

Not supported on this backend (documented divergences): the sqlite
memo caches (every read hits the DB — correctness-first; the node's
hot verify path batches at a higher level), and WAL-specific behaviors.
"""

from __future__ import annotations

import asyncio
import hashlib
from contextlib import asynccontextmanager
from decimal import Decimal
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from ..core.clock import timestamp as now_ts
from ..core.constants import MAX_BLOCK_SIZE_HEX, SMALLEST
from ..core.tx import CoinbaseTx, Tx, TxInput, tx_from_hex
from ..logger import get_logger
from .pgdriver import AsyncpgDriver, MockPgDriver, _epoch, _utc
from .storage import _GOV_TABLES, _INPUT_TABLE, _OUTPUT_TABLE
from .views import StateViews

AnyTx = Union[Tx, CoinbaseTx]

log = get_logger("state.pg")

_COIN_Q = Decimal("0.000001")  # NUMERIC(14,6) quantum (schema.sql)

# Reference schema.sql statements (schema.sql:1-84), one per entry so
# ensure_schema can tolerate partially-created databases.
PG_SCHEMA = [
    """CREATE TABLE IF NOT EXISTS blocks (
        id SERIAL PRIMARY KEY,
        hash CHAR(64) UNIQUE,
        content TEXT NOT NULL,
        address VARCHAR(128) NOT NULL,
        random BIGINT NOT NULL,
        difficulty NUMERIC(3, 1) NOT NULL,
        reward NUMERIC(14, 6) NOT NULL,
        timestamp TIMESTAMP(0)
    )""",
    """CREATE TABLE IF NOT EXISTS transactions (
        block_hash CHAR(64) NOT NULL REFERENCES blocks(hash) ON DELETE CASCADE,
        tx_hash CHAR(64) UNIQUE,
        tx_hex TEXT,
        inputs_addresses TEXT[],
        outputs_addresses TEXT[],
        outputs_amounts BIGINT[],
        fees NUMERIC(14, 6) NOT NULL
    )""",
    """CREATE TABLE IF NOT EXISTS unspent_outputs (
        tx_hash CHAR(64) REFERENCES transactions(tx_hash) ON DELETE CASCADE,
        index SMALLINT NOT NULL,
        address TEXT NULL,
        is_stake BOOLEAN
    )""",
    """CREATE TABLE IF NOT EXISTS pending_transactions (
        tx_hash CHAR(64) UNIQUE,
        tx_hex TEXT,
        inputs_addresses TEXT[],
        fees NUMERIC(14, 6) NOT NULL,
        propagation_time TIMESTAMP(0) NOT NULL DEFAULT NOW()
    )""",
    """CREATE TABLE IF NOT EXISTS pending_spent_outputs (
        tx_hash CHAR(64) REFERENCES transactions(tx_hash) ON DELETE CASCADE,
        index SMALLINT NOT NULL
    )""",
] + [
    f"""CREATE TABLE IF NOT EXISTS {t} (
        tx_hash CHAR(64) REFERENCES transactions(tx_hash) ON DELETE CASCADE,
        index SMALLINT NOT NULL,
        address TEXT NULL
    )"""
    for t in _GOV_TABLES
] + [
    "CREATE INDEX IF NOT EXISTS tx_hash_idx ON unspent_outputs (tx_hash)",
    "CREATE INDEX IF NOT EXISTS block_hash_idx ON transactions (block_hash)",
] + [
    # Beyond-reference migration (both statements idempotent, and a
    # pre-existing uPow database picks the column up on first boot): a
    # monotonic journal sequence for the mempool's change stamp.  pg has
    # no rowid, and (COUNT, MAX(tx_hash)) is blind to a delete+insert
    # that replaces a non-max row at the same count — MAX(journal_seq)
    # moves on every insert because the sequence never hands a value
    # out twice.  Reference writers that INSERT without naming the
    # column draw the default, so wallet-CLI interop is unchanged.
    "CREATE SEQUENCE IF NOT EXISTS pending_journal_seq",
    "ALTER TABLE pending_transactions ADD COLUMN IF NOT EXISTS"
    " journal_seq BIGINT DEFAULT nextval('pending_journal_seq')",
]


def _coins(units: int) -> Decimal:
    """int smallest-units -> NUMERIC(14,6) coin value, quantized the way
    the column would: PostgreSQL numeric rounds half AWAY FROM ZERO
    (Decimal's default half-even would store 0.0000005 coins as 0 where
    the reference's server stores 0.000001)."""
    from decimal import ROUND_HALF_UP

    return (Decimal(units) / SMALLEST).quantize(_COIN_Q,
                                                rounding=ROUND_HALF_UP)


def _units(coins: Optional[Decimal]) -> int:
    return int(Decimal(coins or 0) * SMALLEST)


class PgChainState(StateViews):
    """ChainState-compatible storage over the reference PostgreSQL schema.

    ``driver`` defaults to asyncpg on ``dsn``; tests inject
    :class:`MockPgDriver`.
    """

    def __init__(self, dsn: str = "", driver=None,
                 emission_path: Optional[str] = None):
        self.drv = driver if driver is not None else AsyncpgDriver(dsn)
        self.path = dsn
        self.emission_path = emission_path
        self._dev_index: Optional[Dict[str, object]] = None
        self._in_atomic = False
        # transaction-scope exclusivity: every DB call is a yield point
        # now (awaitable driver), so without this a concurrent writer's
        # statements would land INSIDE another task's open BEGIN and get
        # committed/rolled back with it.  Lazy: asyncio.Lock binds to
        # the running loop on first acquire.  _txn_owner distinguishes
        # the task that opened the transaction (its nested writes join
        # it) from foreign tasks (which must wait on the lock).
        self._write_lock = None
        self._txn_owner = None
        self._index_mutations = 0  # dirty counter: rollback only pays
        # the full index resync if the transaction actually touched it
        self._pending_gen = 0  # bumped on every LOCAL mempool mutation
        self.reinject_reorg_txs = False  # Node flips this from config
        # reorg notification for the hot-state read cache — same hook
        # as the sqlite backend (ChainState.on_blocks_removed)
        self.on_blocks_removed = None
        # cold-block archive fallthrough (upow_tpu/archive/,
        # docs/ARCHIVE.md) — same seam as the sqlite backend
        self.archive = None

    def _writer(self):
        if self._write_lock is None:
            self._write_lock = asyncio.Lock()
        return self._write_lock

    def _owns_txn(self) -> bool:
        return self._in_atomic and self._txn_owner is asyncio.current_task()

    @asynccontextmanager
    async def _open_txn(self, commit: bool = True):
        """The single home of writer-lock + transaction bookkeeping:
        acquire the lock, mark this task as owner (its nested writes
        join the transaction), BEGIN, then COMMIT — or ROLLBACK on
        error or when ``commit=False`` (replay).  Any rollback resyncs
        the device index (in-memory mutations from the discarded
        transaction would otherwise turn into definitive false
        negatives in the membership prefilter), still under the lock."""
        async with self._writer():
            self._in_atomic = True
            self._txn_owner = asyncio.current_task()
            rolled_back = False
            mutations_at_entry = self._index_mutations
            try:
                await self.drv.abegin()
                yield
                if commit:
                    await self.drv.acommit()
                else:
                    rolled_back = True
                    await self.drv.arollback()
            except BaseException:
                rolled_back = True
                await self.drv.arollback()
                raise
            finally:
                # also covers a failed BEGIN: leaking the owner flags
                # would let this task's later writes bypass the lock
                self._in_atomic = False
                self._txn_owner = None
                if rolled_back:
                    self._bump_fees_gen()  # memos may hold discarded rows
                if rolled_back and \
                        self._index_mutations != mutations_at_entry:
                    # in-memory index mutations from the discarded
                    # transaction would otherwise become definitive
                    # false negatives in the membership prefilter
                    await self._aindex_rebuild()

    @asynccontextmanager
    async def _txn(self):
        """Group a multi-statement mutation into one transaction unless
        this task already holds one (nested _txn — e.g. rebuild_utxos →
        add_transaction_outputs — joins it).  The sqlite backend gets
        transactionality implicitly (sqlite3 defers commit until
        _commit()); with per-statement autocommit a crash mid-reorg
        would otherwise leave torn chain state."""
        if self._owns_txn():
            yield
            return
        async with self._open_txn():
            yield

    @asynccontextmanager
    async def _write_guard(self):
        """Exclusivity without a transaction wrapper, for writes that
        are a single (auto-committed) statement — BEGIN/COMMIT would be
        two extra round trips for no additional guarantee."""
        if self._owns_txn():
            yield
            return
        async with self._writer():
            yield

    @asynccontextmanager
    async def replay_transaction(self):
        """Open a transaction, run the body joined to it, and ALWAYS
        roll back at exit — the reindex --check primitive."""
        async with self._open_txn(commit=False):
            yield

    def ensure_schema(self) -> None:
        """Create any missing tables (idempotent; a pre-existing uPow
        database passes through untouched)."""
        if getattr(self.drv, "schema_preinstalled", False):
            return  # the mock creates its sqlite-dialect schema itself
        for stmt in PG_SCHEMA:
            self.drv.execute(stmt)
        # the reference schema also declares a composite type
        # (schema.sql:22-25).  CREATE TYPE has no IF NOT EXISTS, so guard
        # server-side (locale-independent, unlike matching the error
        # text); the sqlite mock has no composite types — skip there.
        if getattr(self.drv, "supports_composite_types", True):
            self.drv.execute(
                "DO $$ BEGIN"
                " CREATE TYPE tx_output AS (tx_hash CHAR(64), index SMALLINT);"
                " EXCEPTION WHEN duplicate_object THEN NULL;"
                " END $$")

    def close(self):
        self.drv.close()

    @asynccontextmanager
    async def atomic(self):
        """One transaction around a whole block acceptance (the driver
        autocommits individual statements outside of this).  Holds the
        writer lock for the duration: reads may interleave between the
        transaction's statements (same semantics as the sqlite backend's
        shared connection), foreign writes may not."""
        async with self._open_txn():
            yield

    # ------------------------------------------------------ device index --

    def enable_device_index(self) -> None:
        """Same device-resident membership index as the sqlite backend
        (storage.py enable_device_index).  Sync (blocking) — called once
        at node boot; runtime resyncs go through :meth:`_aindex_rebuild`.
        The reference pg schema carries no amount column on
        unspent_outputs, so bulk loads seed the resident value store
        with zeros; incremental adds (which decode the tx) thread real
        amounts.  Membership never depends on the value lanes."""
        if not self._device_index_usable():
            return
        from .device_index import DeviceUtxoIndex

        self._dev_index = {}
        for table in ("unspent_outputs",) + _GOV_TABLES:
            rows = self.drv.fetch(f'SELECT tx_hash, "index" FROM {table}')
            self._dev_index[table] = DeviceUtxoIndex(
                (r["tx_hash"], r["index"]) for r in rows)

    def _device_index_usable(self) -> bool:
        from ..benchutil import probed_platform_cached

        if probed_platform_cached(timeout=90.0) is None:
            import logging

            logging.getLogger("upow_tpu.state").warning(
                "jax backend init hung/failed; device UTXO index disabled")
            self._dev_index = None
            return False
        return True

    def _index_add(self, table, outpoints, values=None):
        if self._dev_index is not None:
            self._index_mutations += 1
            self._dev_index[table].add(outpoints, values)

    def _index_remove(self, table, outpoints):
        if self._dev_index is not None:
            self._index_mutations += 1
            self._dev_index[table].remove(outpoints)

    def resident_indexes(self):
        """Per-table DeviceUtxoIndex map when enabled, else None — the
        accept path's gate for the fused resident probe."""
        return self._dev_index

    def index_stats(self):
        """Aggregate resident-index telemetry (same shape as the sqlite
        backend's); None when the index is disabled."""
        if not self._dev_index:
            return None
        agg = {"entries": 0, "resident_bytes": 0, "probes": 0,
               "shadow_consults": 0, "twin_fingerprints": 0}
        for index in self._dev_index.values():
            s = index.stats()
            for k in agg:
                agg[k] += s[k]
        return agg

    async def _aindex_rebuild(self):
        """Resync the device index from the live tables without blocking
        the event loop (reorg rollback / replay paths)."""
        if self._dev_index is None or not self._device_index_usable():
            return
        from .device_index import DeviceUtxoIndex

        fresh = {}
        for table in ("unspent_outputs",) + _GOV_TABLES:
            rows = await self.drv.afetch(
                f'SELECT tx_hash, "index" FROM {table}')
            fresh[table] = DeviceUtxoIndex(
                (r["tx_hash"], r["index"]) for r in rows)
        self._dev_index = fresh

    # ------------------------------------------------------------- blocks --

    async def add_block(self, block_id: int, block_hash: str, content: str,
                        address: str, nonce: int, difficulty, reward: int,
                        ts: int) -> None:
        async with self._write_guard():
            await self.drv.aexecute(
                "INSERT INTO blocks (id, hash, content, address, random,"
                " difficulty, reward, timestamp)"
                " VALUES ($1,$2,$3,$4,$5,$6,$7,$8)",
                (block_id, block_hash, content, address, nonce,
                 Decimal(str(difficulty)), _coins(reward), _utc(ts)),
            )

    @staticmethod
    def _block_dict(r) -> dict:
        return {
            "id": r["id"],
            "hash": r["hash"],
            "content": r["content"],
            "address": r["address"],
            "random": r["random"],
            "difficulty": Decimal(r["difficulty"]),
            "reward": Decimal(r["reward"]),
            "timestamp": _epoch(r["timestamp"]),
        }

    @staticmethod
    def _archive_block_dict(b: list) -> dict:
        """Canonical archive block row -> the hot _block_dict shape
        (reward int smallest-units -> NUMERIC-coin Decimal, matching
        what the column would have held)."""
        return {
            "id": b[0],
            "hash": b[1],
            "content": b[2],
            "address": b[3],
            "random": b[4],
            "difficulty": Decimal(b[5]),
            "reward": _coins(b[6]),
            "timestamp": b[7],
        }

    async def get_block(self, block_hash: str) -> Optional[dict]:
        rows = await self.drv.afetch(
            "SELECT * FROM blocks WHERE hash = $1", (block_hash,))
        if not rows and self.archive is not None:
            b = await self.archive.block_by_hash(block_hash)
            return self._archive_block_dict(b) if b else None
        return self._block_dict(rows[0]) if rows else None

    async def get_block_by_id(self, block_id: int) -> Optional[dict]:
        rows = await self.drv.afetch(
            "SELECT * FROM blocks WHERE id = $1", (block_id,))
        if not rows and self.archive is not None:
            b = await self.archive.block_by_height(block_id)
            return self._archive_block_dict(b) if b else None
        return self._block_dict(rows[0]) if rows else None

    async def get_last_block(self) -> Optional[dict]:
        rows = await self.drv.afetch("SELECT * FROM blocks ORDER BY id DESC LIMIT 1")
        return self._block_dict(rows[0]) if rows else None

    async def get_next_block_id(self) -> int:
        rows = await self.drv.afetch("SELECT MAX(id) AS m FROM blocks")
        return (rows[0]["m"] or 0) + 1

    async def get_blocks(self, offset: int, limit: int,
                         tx_details: bool = False,
                         size_capped: bool = False) -> List[dict]:
        """Blocks with embedded full transactions (database.py:380-408).

        One transactions query for the whole page (grouped host-side) —
        a 1000-block sync page is 2 round trips on the network-attached
        driver, not 1001 (``tx_details`` swaps tx hex for
        explorer-shaped dicts at the reference's per-tx lookup cost).
        ``size_capped`` truncates the page at 8 full blocks' worth of
        hex — passed by the HTTP serving layer only, so internal
        callers (the reorg-window scan) always see the full window
        (divergence note in the sqlite twin's docstring)."""
        rows = await self.drv.afetch(
            "SELECT * FROM blocks WHERE id >= $1 ORDER BY id LIMIT $2",
            (offset, limit))
        by_hash: dict = {r["hash"]: [] for r in rows}
        if rows:
            txs = await self.drv.afetch(
                "SELECT block_hash, tx_hash, tx_hex FROM transactions"
                " WHERE block_hash = ANY($1)", (list(by_hash),))
            for t in txs:
                by_hash[t["block_hash"]].append((t["tx_hash"], t["tx_hex"]))
        entries = [(r["id"], self._block_dict(r), by_hash[r["hash"]])
                   for r in rows]
        if self.archive is not None:
            cov = await self.archive.coverage()
            if cov is not None and offset <= cov[1]:
                # overlay archived blocks into the page (hot wins on
                # overlap; see the sqlite twin's note)
                hot_ids = {e[0] for e in entries}
                for b, atxs in await self.archive.span(
                        offset, offset + limit - 1):
                    if b[0] not in hot_ids:
                        entries.append((b[0], self._archive_block_dict(b),
                                        [(t[1], t[2]) for t in atxs]))
                entries.sort(key=lambda e: e[0])
                entries = entries[:limit]
        out = []
        size = 0
        for _bid, block, txs_b in entries:
            size += sum(len(h) for _th, h in txs_b)
            if size_capped and size > MAX_BLOCK_SIZE_HEX * 8:
                break
            block = dict(block)
            block["difficulty"] = float(block["difficulty"])
            block["reward"] = str(block["reward"])
            if tx_details:
                # per-tx lookups are inherent to the explorer shape
                # (see the sqlite twin's note); drop reorg-raced Nones
                nice = [await self.get_nice_transaction(th)
                        for th, _h in txs_b]
                tx_list = [t for t in nice if t is not None]
            else:
                tx_list = [h for _th, h in txs_b]
            out.append({"block": block, "transactions": tx_list})
        return out

    async def remove_blocks(self, from_block_id: int) -> None:
        """Reorg rollback (database.py:146-169), same dependent-tx filter
        as the sqlite backend."""
        async with self._txn():
            # the doomed-tx snapshot must share the writer-lock scope
            # with the deletes: every driver call yields, so a snapshot
            # taken outside could miss a block accepted concurrently at
            # >= from_block_id — DELETE FROM blocks would then cascade
            # its transactions without restoring their spent UTXOs
            rows = await self.drv.afetch(
                "SELECT t.tx_hex FROM transactions t JOIN blocks b"
                " ON t.block_hash = b.hash WHERE b.id >= $1",
                (from_block_id,))
            txs = [tx_from_hex(r["tx_hex"], check_signatures=False)
                   for r in rows]
            from .. import trace

            trace.event("reorg", from_block=from_block_id,
                        removed_txs=len(txs))
            created = [tx.hash() for tx in txs]
            for table in ("unspent_outputs",) + _GOV_TABLES:
                await self.drv.aexecutemany(
                    f"DELETE FROM {table} WHERE tx_hash = $1",
                    [(h,) for h in created])
            # O(delta) index maintenance (ISSUE 11): delta-remove the
            # removed txs' outputs by class (absent = no-op), mirroring
            # the sqlite backend; restores delta-add below, so the
            # wholesale post-reorg resync is gone from the happy path.
            # The _open_txn rollback rebuild still covers failures.
            if self._dev_index is not None:
                doomed_by_table: Dict[str, list] = {}
                for tx in txs:
                    h = tx.hash()
                    for index, out in enumerate(tx.outputs):
                        doomed_by_table.setdefault(
                            _OUTPUT_TABLE[out.output_type], []).append(
                                (h, index))
                for table, outpoints in doomed_by_table.items():
                    self._index_remove(table, outpoints)
            created_set = set(created)
            restore = [
                tx_input for tx in txs if not tx.is_coinbase
                for tx_input in tx.inputs
                if tx_input.tx_hash not in created_set
            ]
            await self._restore_spent_outputs(restore)
            await self.drv.aexecutemany(
                "DELETE FROM transactions WHERE tx_hash = $1",
                [(h,) for h in created])
            await self.drv.aexecute(
                "DELETE FROM blocks WHERE id >= $1", (from_block_id,))
            self._bump_fees_gen()
        if self.on_blocks_removed is not None:
            self.on_blocks_removed(from_block_id)

    async def _restore_spent_outputs(self, inputs: List[TxInput]) -> None:
        for tx_input in inputs:
            src = await self.get_transaction(tx_input.tx_hash,
                                             include_pending=False)
            if src is None:
                continue
            out = src.outputs[tx_input.index]
            table = _OUTPUT_TABLE[out.output_type]
            exists = await self.drv.afetch(
                f'SELECT 1 AS x FROM {table} WHERE tx_hash = $1'
                f' AND "index" = $2', (tx_input.tx_hash, tx_input.index))
            if exists:
                continue
            if table == "unspent_outputs":
                await self.drv.aexecute(
                    'INSERT INTO unspent_outputs (tx_hash, "index", address,'
                    " is_stake) VALUES ($1,$2,$3,$4)",
                    (tx_input.tx_hash, tx_input.index, out.address,
                     bool(out.is_stake)))
            else:
                await self.drv.aexecute(
                    f'INSERT INTO {table} (tx_hash, "index", address)'
                    " VALUES ($1,$2,$3)",
                    (tx_input.tx_hash, tx_input.index, out.address))
            # delta-add: the existence check above already filtered
            # duplicate restores, so the index stays in lockstep
            self._index_add(table, [(tx_input.tx_hash, tx_input.index)],
                            values=[(out.amount, out.address or "", 0)])

    # ------------------------------------------------------- transactions --

    async def add_transactions(self, txs: Sequence[AnyTx],
                               block_hash: str) -> None:
        rows = []
        for tx in txs:
            inputs_addresses = [] if tx.is_coinbase else [
                await self.resolve_output_address(i.tx_hash, i.index) or ""
                for i in tx.inputs
            ]
            fees = 0 if tx.is_coinbase else await self.tx_fees(tx)
            rows.append((
                block_hash, tx.hash(), tx.hex(),
                inputs_addresses,
                [o.address for o in tx.outputs],
                [o.amount for o in tx.outputs],
                _coins(fees),
            ))
        async with self._write_guard():  # executemany is implicitly
            # transactional in asyncpg; only exclusivity is needed
            await self.drv.aexecutemany(
                "INSERT INTO transactions (block_hash, tx_hash, tx_hex,"
                " inputs_addresses, outputs_addresses, outputs_amounts, fees)"
                " VALUES ($1,$2,$3,$4,$5,$6,$7)"
                " ON CONFLICT (tx_hash) DO UPDATE SET block_hash ="
                " EXCLUDED.block_hash", rows)

    async def get_transaction(self, tx_hash: str,
                              include_pending: bool = False) -> Optional[AnyTx]:
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM transactions WHERE tx_hash = $1", (tx_hash,))
        if not rows and include_pending:
            rows = await self.drv.afetch(
                "SELECT tx_hex FROM pending_transactions WHERE tx_hash = $1",
                (tx_hash,))
        if not rows and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                return tx_from_hex(hit[0][2], check_signatures=False)
        return tx_from_hex(rows[0]["tx_hex"], check_signatures=False) \
            if rows else None

    async def get_transaction_info(self, tx_hash: str) -> Optional[dict]:
        rows = await self.drv.afetch(
            "SELECT * FROM transactions WHERE tx_hash = $1", (tx_hash,))
        if not rows:
            if self.archive is not None:
                hit = await self.archive.tx_by_hash(tx_hash)
                if hit is not None:
                    t = hit[0]
                    return {
                        "block_hash": t[0], "tx_hash": t[1],
                        "tx_hex": t[2], "inputs_addresses": t[3],
                        "outputs_addresses": t[4],
                        "outputs_amounts": t[5], "fees": t[6],
                    }
            return None
        r = rows[0]
        return {
            "block_hash": r["block_hash"],
            "tx_hash": r["tx_hash"],
            "tx_hex": r["tx_hex"],
            "inputs_addresses": list(r["inputs_addresses"]),
            "outputs_addresses": list(r["outputs_addresses"]),
            "outputs_amounts": list(r["outputs_amounts"]),
            "fees": _units(r["fees"]),
        }

    async def get_block_transactions(self, block_hash: str,
                                     hex_only: bool = False) -> List:
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM transactions WHERE block_hash = $1",
            (block_hash,))
        if not rows and self.archive is not None:
            # pruned blocks lose their ENTIRE tx set (never split)
            atxs = await self.archive.txs_for_block(block_hash)
            if atxs:
                if hex_only:
                    return [t[2] for t in atxs]
                return [tx_from_hex(t[2], check_signatures=False)
                        for t in atxs]
        if hex_only:
            return [r["tx_hex"] for r in rows]
        return [tx_from_hex(r["tx_hex"], check_signatures=False) for r in rows]

    async def resolve_output_address(self, tx_hash: str,
                                     index: int) -> Optional[str]:
        rows = await self.drv.afetch(
            "SELECT outputs_addresses FROM transactions WHERE tx_hash = $1",
            (tx_hash,))
        if rows:
            addresses = list(rows[0]["outputs_addresses"])
            return addresses[index] if index < len(addresses) else None
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM pending_transactions WHERE tx_hash = $1",
            (tx_hash,))
        if not rows:
            if self.archive is not None:
                hit = await self.archive.tx_by_hash(tx_hash)
                if hit is not None:
                    addresses = hit[0][4]
                    return (addresses[index]
                            if index < len(addresses) else None)
            return None
        tx = tx_from_hex(rows[0]["tx_hex"], check_signatures=False)
        return tx.outputs[index].address if index < len(tx.outputs) else None

    async def get_output_amount(self, tx_hash: str,
                                index: int) -> Optional[int]:
        rows = await self.drv.afetch(
            "SELECT outputs_amounts FROM transactions WHERE tx_hash = $1",
            (tx_hash,))
        if rows:
            amounts = list(rows[0]["outputs_amounts"])
            return amounts[index] if index < len(amounts) else None
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM pending_transactions WHERE tx_hash = $1",
            (tx_hash,))
        if not rows:
            if self.archive is not None:
                hit = await self.archive.tx_by_hash(tx_hash)
                if hit is not None:
                    amounts = hit[0][5]
                    return (amounts[index]
                            if index < len(amounts) else None)
            return None
        tx = tx_from_hex(rows[0]["tx_hex"], check_signatures=False)
        return tx.outputs[index].amount if index < len(tx.outputs) else None

    # ------------------------------------------------------------ mempool --

    async def add_pending_transaction(self, tx: Tx) -> Optional[int]:
        """Insert one journal row; returns its journal_seq (see the
        sqlite twin — the value the stamp's MAX(journal_seq) takes when
        no foreign writer interleaved, used by Mempool.reconcile's
        delta prediction).  Read back by tx_hash inside the same
        transaction: a row's sequence is immutable once assigned, so
        the read cannot be corrupted by concurrent writers."""
        inputs_addresses = [
            await self.resolve_output_address(i.tx_hash, i.index) or ""
            for i in tx.inputs
        ]
        fees = await self.tx_fees(tx)
        async with self._txn():
            await self.drv.aexecute(
                "INSERT INTO pending_transactions (tx_hash, tx_hex,"
                " inputs_addresses, fees, propagation_time)"
                " VALUES ($1,$2,$3,$4,$5)",
                (tx.hash(), tx.hex(), inputs_addresses, _coins(fees),
                 _utc(now_ts())))
            await self.drv.aexecutemany(
                'INSERT INTO pending_spent_outputs (tx_hash, "index")'
                " VALUES ($1,$2)",
                [(i.tx_hash, i.index) for i in tx.inputs])
            rows = await self.drv.afetch(
                "SELECT journal_seq AS s FROM pending_transactions"
                " WHERE tx_hash = $1", (tx.hash(),))
        self._pending_gen += 1
        return rows[0]["s"] if rows else None

    async def _pending_decoded(self) -> Dict[str, Tx]:
        rows = await self.drv.afetch(
            "SELECT tx_hash, tx_hex FROM pending_transactions")
        return {
            r["tx_hash"]: tx_from_hex(r["tx_hex"], check_signatures=False)
            for r in rows
        }

    async def pending_transaction_exists(self, tx_hash: str) -> bool:
        return bool(await self.drv.afetch(
            "SELECT 1 AS x FROM pending_transactions WHERE tx_hash = $1",
            (tx_hash,)))

    async def get_pending_transactions_limit(
        self, limit_hex_chars: int = 4096 * 1024, hex_only: bool = False
    ) -> List:
        """Fee-rate-ordered mempool slice capped by total hex size
        (database.py:171-186).

        Ordering reads the NUMERIC(14,6) fees column, so fee rates are
        quantized to 100-smallest-unit granularity — EXACTLY what the
        reference node does with this schema (its ORDER BY reads the
        same lossy column).  The sqlite backend orders by exact integer
        fees; a pg-backed node reproduces the reference's block-building
        choices instead.  Consensus is unaffected (fees in accepted
        blocks are recomputed from tx amounts)."""
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM pending_transactions ORDER BY"
            " fees / LENGTH(tx_hex) DESC, tx_hash")
        out, total = [], 0
        for r in rows:
            if total + len(r["tx_hex"]) > limit_hex_chars:
                break
            total += len(r["tx_hex"])
            out.append(r["tx_hex"])
        if hex_only:
            return out
        return [tx_from_hex(h, check_signatures=False) for h in out]

    async def get_pending_transactions_by_hash(self,
                                               hashes: List[str]) -> List[str]:
        # chunked IN (...) — one round trip per 500 hashes instead of
        # one per hash; request order (and duplicates) preserved
        found: Dict[str, str] = {}
        for i in range(0, len(hashes), 500):
            chunk = list(dict.fromkeys(hashes[i:i + 500]))
            ph = ",".join(f"${j + 1}" for j in range(len(chunk)))
            rows = await self.drv.afetch(
                "SELECT tx_hash, tx_hex FROM pending_transactions"
                f" WHERE tx_hash IN ({ph})", chunk)
            for r in rows:
                found[r["tx_hash"]] = r["tx_hex"]
        return [found[h] for h in hashes if h in found]

    async def pending_journal_stamp(self) -> tuple:
        """Cheap change stamp over the pending journal (see the sqlite
        twin).  MAX(journal_seq) plays the rowid's role, and is
        strictly stronger: the sequence never reissues a value, so a
        delete+insert rewrite always moves the max (sqlite rowid can be
        reused when the max row is deleted).  The local generation
        counter still covers same-process rewrites.  Rows predating the
        journal_seq migration carry NULL and are masked by COALESCE
        until the first post-migration insert."""
        rows = await self.drv.afetch(
            "SELECT COUNT(*) AS c, COALESCE(MAX(journal_seq), 0) AS m"
            " FROM pending_transactions")
        return (rows[0]["c"], rows[0]["m"], self._pending_gen)

    async def load_pending_journal(self) -> List[dict]:
        """Full journal rows for pool recovery/reconcile; NUMERIC fees
        come back in coins and are converted to integer units."""
        rows = await self.drv.afetch(
            "SELECT tx_hash, tx_hex, fees FROM pending_transactions")
        return [{"tx_hash": r["tx_hash"], "tx_hex": r["tx_hex"],
                 "fees": _units(r["fees"])} for r in rows]

    async def get_pending_spent_outpoints(self, outpoints=None) -> set:
        """Pending-spent overlay; ``outpoints`` narrows the fetch to one
        tx's inputs (see the sqlite twin's rationale — full scans per
        intake tx are quadratic in mempool depth)."""
        if outpoints is None:
            rows = await self.drv.afetch(
                'SELECT tx_hash, "index" FROM pending_spent_outputs')
            return {(r["tx_hash"], r["index"]) for r in rows}
        want = {tuple(o) for o in outpoints}
        if not want:
            return set()
        rows = await self.drv.afetch(
            'SELECT tx_hash, "index" FROM pending_spent_outputs'
            " WHERE tx_hash = ANY($1)", (list({h for h, _ in want}),))
        return {(r["tx_hash"], r["index"]) for r in rows} & want

    async def remove_pending_transactions_by_hash(self,
                                                  hashes: List[str]) -> None:
        async with self._txn():
            await self._remove_pending_by_hash_locked(hashes)
        self._pending_gen += 1

    async def _remove_pending_by_hash_locked(self, hashes: List[str]) -> None:
        for i in range(0, len(hashes), 500):
            chunk = hashes[i:i + 500]
            ph = ",".join(f"${j + 1}" for j in range(len(chunk)))
            rows = await self.drv.afetch(
                "SELECT tx_hex FROM pending_transactions"
                f" WHERE tx_hash IN ({ph})", chunk)
            spent = []
            for r in rows:
                tx = tx_from_hex(r["tx_hex"], check_signatures=False)
                if not tx.is_coinbase:
                    spent.extend((inp.tx_hash, inp.index) for inp in tx.inputs)
            if spent:
                await self.drv.aexecutemany(
                    "DELETE FROM pending_spent_outputs"
                    ' WHERE tx_hash = $1 AND "index" = $2', spent)
            await self.drv.aexecute(
                f"DELETE FROM pending_transactions WHERE tx_hash IN ({ph})",
                chunk)

    async def remove_pending_transactions(self) -> None:
        async with self._txn():
            await self.drv.aexecute("DELETE FROM pending_transactions")
            await self.drv.aexecute("DELETE FROM pending_spent_outputs")
        self._pending_gen += 1

    async def get_pending_transactions_count(self) -> int:
        rows = await self.drv.afetch(
            "SELECT COUNT(*) AS c FROM pending_transactions")
        return rows[0]["c"]

    async def get_need_propagate_transactions(self,
                                              older_than: int = 300) -> List[str]:
        """Piggyback re-propagation queue (database.py:188-207)."""
        rows = await self.drv.afetch(
            "SELECT tx_hex FROM pending_transactions"
            " WHERE propagation_time < $1",
            (_utc(now_ts() - older_than),))
        return [r["tx_hex"] for r in rows]

    async def update_pending_transaction_propagation(self,
                                                     tx_hash: str) -> None:
        async with self._write_guard():
            await self.drv.aexecute(
                "UPDATE pending_transactions SET propagation_time = $1"
                " WHERE tx_hash = $2", (_utc(now_ts()), tx_hash))

    # --------------------------------------------------------------- UTXO --

    async def add_transaction_outputs(self, txs: Sequence[AnyTx]) -> None:
        """Route outputs into their UTXO-class table (database.py:524-580).
        Delete-then-insert emulates the sqlite backend's REPLACE — the
        reference tables have no outpoint uniqueness constraint.  Grouped
        into one executemany per table so an 8k-tx block costs a handful
        of driver round trips, not one per output."""
        by_table: Dict[str, list] = {}
        for tx in txs:
            h = tx.hash()
            for index, out in enumerate(tx.outputs):
                table = _OUTPUT_TABLE[out.output_type]
                by_table.setdefault(table, []).append((h, index, out))
        async with self._txn():
            for table, entries in by_table.items():
                await self.drv.aexecutemany(
                    f'DELETE FROM {table} WHERE tx_hash = $1'
                    ' AND "index" = $2',
                    [(h, i) for h, i, _ in entries])
                if table == "unspent_outputs":
                    await self.drv.aexecutemany(
                        'INSERT INTO unspent_outputs (tx_hash, "index",'
                        " address, is_stake) VALUES ($1,$2,$3,$4)",
                        [(h, i, o.address, bool(o.is_stake))
                         for h, i, o in entries])
                else:
                    await self.drv.aexecutemany(
                        f'INSERT INTO {table} (tx_hash, "index", address)'
                        " VALUES ($1,$2,$3)",
                        [(h, i, o.address) for h, i, o in entries])
                self._index_add(table, [(h, i) for h, i, _ in entries],
                                values=[(o.amount, o.address or "", 0)
                                        for _h, _i, o in entries])

    async def remove_outputs(self, txs: Sequence[AnyTx]) -> None:
        """Spend inputs from the table their tx type targets
        (database.py:589-622).  Grouped per table: one DELETE
        executemany + one batched index apply per UTXO class."""
        by_table: Dict[str, list] = {}
        for tx in txs:
            if tx.is_coinbase:
                continue
            table = _INPUT_TABLE.get(tx.transaction_type, "unspent_outputs")
            by_table.setdefault(table, []).extend(
                (i.tx_hash, i.index) for i in tx.inputs)
        async with self._txn():
            for table, outpoints in by_table.items():
                await self.drv.aexecutemany(
                    f'DELETE FROM {table} WHERE tx_hash = $1'
                    ' AND "index" = $2',
                    outpoints)
                self._index_remove(table, outpoints)

    async def get_unspent_outpoints(self,
                                    table: str = "unspent_outputs") -> set:
        rows = await self.drv.afetch(f'SELECT tx_hash, "index" FROM {table}')
        return {(r["tx_hash"], r["index"]) for r in rows}

    async def outpoints_exist(self, outpoints: List[Tuple[str, int]],
                              table: str = "unspent_outputs") -> List[bool]:
        """Batched membership test, same shape as the sqlite backend's
        (storage.py outpoints_exist).  With the device index enabled the
        answer is exact and SQL-free (the index's host map resolves
        fingerprint twins); the index assumes this node is the sole
        writer of the UTXO tables — the same assumption the journal and
        block-accept paths already make."""
        if not outpoints:
            return []
        if self._dev_index is not None and table in self._dev_index:
            present = self._dev_index[table].contains_batch(
                [tuple(o) for o in outpoints])
            return [bool(p) for p in present]
        return await self._outpoints_exist_sql(outpoints, table)

    async def _outpoints_exist_sql(self, outpoints, table) -> List[bool]:
        if not outpoints:
            return []
        found: set = set()
        CHUNK = 400
        for off in range(0, len(outpoints), CHUNK):
            chunk = outpoints[off:off + CHUNK]
            placeholders = ",".join(
                f"(${2 * j + 1},${2 * j + 2})" for j in range(len(chunk)))
            params = [v for o in chunk for v in o]
            rows = await self.drv.afetch(
                f'SELECT tx_hash, "index" FROM {table} WHERE'
                f' (tx_hash, "index") IN (VALUES {placeholders})', params)
            found.update((r["tx_hash"], r["index"]) for r in rows)
        return [tuple(o) in found for o in outpoints]

    async def get_table_outpoints_hash(self, table: str) -> str:
        rows = await self.drv.afetch(
            f'SELECT tx_hash, "index" FROM {table}'
            ' ORDER BY tx_hash, "index"')
        h = hashlib.sha256()
        for r in rows:
            h.update(f"{r['tx_hash']}{r['index']}".encode())
        return h.hexdigest()

    # ------------------------------------------------------ address views --

    async def _amounts_for(self, rows) -> List[dict]:
        """Attach amounts to outpoint rows carrying outputs_amounts
        arrays (the reference's join-based amount resolution)."""
        out = []
        for r in rows:
            amounts = list(r["outputs_amounts"] or [])
            idx = r["index"]
            out.append({
                "tx_hash": r["tx_hash"], "index": idx,
                "address": r["address"],
                "amount": amounts[idx] if idx < len(amounts) else 0,
            })
        return out

    async def _pending_filter(self, rows, check_pending_txs: bool) -> set:
        """Pending-spent overlay narrowed to these rows' outpoints (see
        the sqlite twin — full scans per lookup are quadratic under
        mempool load)."""
        if not check_pending_txs:
            return set()
        # threshold: narrowing wins when the row set is small (intake,
        # per-address lookups); full-table views (registrations,
        # ballots) would ship one bind param per row and invert the
        # cost model — there the one O(overlay) fetch stays cheaper,
        # and the cap also bounds the IN-clause parameter count
        if not rows:
            return set()
        if len(rows) > 256:
            return await self.get_pending_spent_outpoints()
        return await self.get_pending_spent_outpoints(
            [(r["tx_hash"], r["index"]) for r in rows])

    async def get_spendable_outputs(self, address: str,
                                    check_pending_txs: bool = False) -> List[TxInput]:
        rows = await self.drv.afetch(
            'SELECT u.tx_hash, u."index", u.address, u.is_stake,'
            " t.outputs_amounts FROM unspent_outputs u"
            " JOIN transactions t ON t.tx_hash = u.tx_hash"
            " WHERE u.address = $1 AND u.is_stake = $2", (address, False))
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in await self._amounts_for(rows):
            if (r["tx_hash"], r["index"]) in pending:
                continue
            i = TxInput(r["tx_hash"], r["index"])
            i.amount = r["amount"]
            out.append(i)
        return out

    async def get_stake_outputs(self, address: str,
                                check_pending_txs: bool = False) -> List[TxInput]:
        rows = await self.drv.afetch(
            'SELECT u.tx_hash, u."index", u.address, u.is_stake,'
            " t.outputs_amounts FROM unspent_outputs u"
            " JOIN transactions t ON t.tx_hash = u.tx_hash"
            " WHERE u.address = $1 AND u.is_stake = $2", (address, True))
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in await self._amounts_for(rows):
            if (r["tx_hash"], r["index"]) in pending:
                continue
            i = TxInput(r["tx_hash"], r["index"])
            i.amount = r["amount"]
            out.append(i)
        return out

    async def get_address_transactions(self, address: str, limit: int = 50,
                                       offset: int = 0) -> List[dict]:
        if self.archive is None:
            rows = await self.drv.afetch(
                "SELECT t.tx_hash, b.id AS block_id FROM transactions t"
                " JOIN blocks b ON b.hash = t.block_hash"
                " WHERE $1 = ANY(inputs_addresses)"
                " OR $1 = ANY(outputs_addresses)"
                " ORDER BY b.id DESC LIMIT $2 OFFSET $3",
                (address, limit, offset))
            return [dict(r) for r in rows]
        # merge archived matches before paginating (see the sqlite
        # twin's note on why the hot prefix of offset+limit suffices)
        rows = await self.drv.afetch(
            "SELECT t.tx_hash, b.id AS block_id FROM transactions t"
            " JOIN blocks b ON b.hash = t.block_hash"
            " WHERE $1 = ANY(inputs_addresses)"
            " OR $1 = ANY(outputs_addresses)"
            " ORDER BY b.id DESC LIMIT $2",
            (address, offset + limit))
        merged = [dict(r) for r in rows]
        seen = {r["tx_hash"] for r in merged}
        for b, t in await self.archive.address_history(address):
            if t[1] not in seen:
                merged.append({"tx_hash": t[1], "block_id": b[0]})
        merged.sort(key=lambda r: -r["block_id"])
        return merged[offset:offset + limit]

    # --------------------------------------------------------- governance --

    async def get_registered(self, table: str,
                             check_pending_txs: bool = False,
                             pending: Optional[set] = None) -> List[Tuple[str, int]]:
        """(address, registered_at block timestamp) per registration
        output (same contract as storage.py get_registered)."""
        rows = await self.drv.afetch(
            f'SELECT g.tx_hash, g."index", g.address, b.timestamp AS ts'
            f" FROM {table} g"
            " LEFT JOIN transactions t ON t.tx_hash = g.tx_hash"
            " LEFT JOIN blocks b ON b.hash = t.block_hash")
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["index"]) in pending:
                continue
            out.append((r["address"],
                        _epoch(r["ts"]) if r["ts"] is not None else now_ts()))
        return out

    async def get_ballot_by_recipient(self, table: str, recipient: str,
                                      check_pending_txs: bool = False) -> List[dict]:
        """Standing votes FOR ``recipient`` (storage.py
        get_ballot_by_recipient contract; reference database.py:939-1063)."""
        rows = await self.drv.afetch(
            f'SELECT g.tx_hash, g."index", t.outputs_amounts,'
            f" t.inputs_addresses FROM {table} g"
            f" JOIN transactions t ON t.tx_hash = g.tx_hash"
            f" WHERE g.address = $1", (recipient,))
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["index"]) in pending:
                continue
            addrs = list(r["inputs_addresses"])
            amounts = list(r["outputs_amounts"])
            idx = r["index"]
            out.append({
                "tx_hash": r["tx_hash"], "index": idx,
                "voter": addrs[idx] if idx < len(addrs) else None,
                "vote": Decimal(amounts[idx] if idx < len(amounts) else 0)
                / SMALLEST,
            })
        return out

    async def _all_ballot_rows(self, table: str,
                               check_pending_txs: bool = False,
                               pending: Optional[set] = None) -> List[dict]:
        rows = await self.drv.afetch(
            f'SELECT g.tx_hash, g."index", g.address AS recipient,'
            f" t.outputs_amounts, t.inputs_addresses FROM {table} g"
            f" JOIN transactions t ON t.tx_hash = g.tx_hash")
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["index"]) in pending:
                continue
            addrs = list(r["inputs_addresses"])
            amounts = list(r["outputs_amounts"])
            idx = r["index"]
            out.append({
                "tx_hash": r["tx_hash"], "index": idx,
                "recipient": r["recipient"],
                "voter": addrs[idx] if idx < len(addrs) else None,
                "vote": Decimal(amounts[idx] if idx < len(amounts) else 0)
                / SMALLEST,
            })
        return out

    async def _outpoint_listing(self, table: str, address: str,
                                check_pending_txs: bool) -> List[Tuple[str, int]]:
        rows = await self.drv.afetch(
            f'SELECT tx_hash, "index" FROM {table} WHERE address = $1',
            (address,))
        pending = await self._pending_filter(rows, check_pending_txs)
        return [(r["tx_hash"], r["index"]) for r in rows
                if (r["tx_hash"], r["index"]) not in pending]

    async def get_delegates_voting_power(self, address: str,
                                         check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        return await self._outpoint_listing(
            "delegates_voting_power", address, check_pending_txs)

    async def get_inode_registration_outputs(self, address: str,
                                             check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        return await self._outpoint_listing(
            "inode_registration_output", address, check_pending_txs)

    async def get_validators_voting_power(self, address: str,
                                          check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        return await self._outpoint_listing(
            "validators_voting_power", address, check_pending_txs)

    async def get_multiple_address_stakes(
            self, addresses: Iterable[str],
            check_pending_txs: bool = False,
            pending: Optional[set] = None) -> Dict[str, Decimal]:
        """Batch stake query (database.py:1208-1290)."""
        addresses = list(set(addresses))
        if not addresses:
            return {}
        out: Dict[str, Decimal] = {a: Decimal(0) for a in addresses}
        placeholders = ",".join(f"${i + 1}" for i in range(len(addresses)))
        rows = await self.drv.afetch(
            'SELECT u.tx_hash, u."index", u.address, t.outputs_amounts'
            " FROM unspent_outputs u JOIN transactions t"
            " ON t.tx_hash = u.tx_hash"
            f" WHERE u.is_stake = ${len(addresses) + 1}"
            f" AND u.address IN ({placeholders})",
            list(addresses) + [True])
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        for r in await self._amounts_for(rows):
            if (r["tx_hash"], r["index"]) in pending:
                continue
            out[r["address"]] += Decimal(r["amount"]) / SMALLEST
        if check_pending_txs:
            want = set(addresses)
            for tx in (await self._pending_decoded()).values():
                for o in tx.outputs:
                    if o.is_stake and o.address in want:
                        out[o.address] += Decimal(o.amount) / SMALLEST
        return out

    async def get_outputs_by_address(self, table: str, address: str,
                                     check_pending_txs: bool = False,
                                     is_stake: Optional[bool] = None) -> List[dict]:
        sql = (f'SELECT g.tx_hash, g."index", g.address, t.outputs_amounts'
               + (", g.is_stake" if table == "unspent_outputs" else "")
               + f" FROM {table} g JOIN transactions t"
               " ON t.tx_hash = g.tx_hash WHERE g.address = $1")
        params: list = [address]
        if is_stake is not None and table == "unspent_outputs":
            sql += " AND g.is_stake = $2"
            params.append(bool(is_stake))
        rows = await self.drv.afetch(sql, params)
        pending = await self._pending_filter(rows, check_pending_txs)
        return [
            {"tx_hash": r["tx_hash"], "index": r["index"],
             "amount": r["amount"]}
            for r in await self._amounts_for(rows)
            if (r["tx_hash"], r["index"]) not in pending
        ]

    async def get_ballots(self, table: str, recipient: Optional[str] = None,
                          offset: int = 0, limit: int = 100) -> List[dict]:
        """Paged ballot listing (storage.py get_ballots contract)."""
        if recipient is not None:
            rows = await self.drv.afetch(
                f'SELECT g.tx_hash, g."index", g.address,'
                f" t.outputs_amounts, t.inputs_addresses FROM {table} g"
                f" JOIN transactions t ON t.tx_hash = g.tx_hash"
                f' WHERE g.address = $1 ORDER BY g.tx_hash, g."index"'
                f" LIMIT $2 OFFSET $3",
                (recipient, limit, offset))
        else:
            rows = await self.drv.afetch(
                f'SELECT g.tx_hash, g."index", g.address,'
                f" t.outputs_amounts, t.inputs_addresses FROM {table} g"
                f" JOIN transactions t ON t.tx_hash = g.tx_hash"
                f' ORDER BY g.tx_hash, g."index" LIMIT $1 OFFSET $2',
                (limit, offset))
        out = []
        for r in rows:
            addrs = list(r["inputs_addresses"])
            amounts = list(r["outputs_amounts"])
            idx = r["index"]
            out.append({
                "tx_hash": r["tx_hash"], "index": idx,
                "voter": addrs[idx] if idx < len(addrs) else None,
                "recipient": r["address"],
                "vote": Decimal(amounts[idx] if idx < len(amounts) else 0)
                / SMALLEST,
            })
        return out

    async def get_transaction_block_timestamp(self,
                                              tx_hash: str) -> Optional[int]:
        rows = await self.drv.afetch(
            "SELECT b.timestamp AS ts FROM transactions t JOIN blocks b ON"
            " b.hash = t.block_hash WHERE t.tx_hash = $1", (tx_hash,))
        if not rows and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                b = await self.archive.block_by_height(hit[1])
                return b[7] if b else None
        return _epoch(rows[0]["ts"]) if rows else None

    # ---------------------------------------------------- explorer views --

    async def get_nice_transaction(self, tx_hash: str,
                                   address: Optional[str] = None) -> Optional[dict]:
        """Explorer-style decoded transaction (storage.py
        get_nice_transaction contract; reference database.py:1606-1654)."""
        rows = await self.drv.afetch(
            "SELECT t.tx_hash, t.tx_hex, t.inputs_addresses, t.block_hash,"
            " b.id AS block_no, b.timestamp AS block_ts FROM"
            " transactions t JOIN blocks b ON b.hash = t.block_hash"
            " WHERE t.tx_hash = $1", (tx_hash,))
        is_confirm = bool(rows)
        if not rows:
            rows = await self.drv.afetch(
                "SELECT tx_hash, tx_hex, inputs_addresses FROM"
                " pending_transactions WHERE tx_hash = $1", (tx_hash,))
        if not rows and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                t, height = hit
                b = await self.archive.block_by_height(height)
                # plain dict stands in for the driver row (_row_keys
                # handles both; _epoch passes int timestamps through)
                rows = [{"tx_hash": t[1], "tx_hex": t[2],
                         "inputs_addresses": t[3], "block_hash": t[0],
                         "block_no": height,
                         "block_ts": b[7] if b else 0}]
                is_confirm = True
        if not rows:
            return None
        r = rows[0]
        keys = _row_keys(r)
        tx = tx_from_hex(r["tx_hex"], check_signatures=False)
        inputs_addresses = list(r["inputs_addresses"])

        def coins(amount: int) -> float:
            return float(Decimal(amount) / SMALLEST)

        block_ts = _epoch(r["block_ts"]) if "block_ts" in keys else None
        if tx.is_coinbase:
            out = {
                "is_coinbase": True, "hash": r["tx_hash"],
                "block_hash": r["block_hash"] if "block_hash" in keys else None,
                "block_no": r["block_no"] if "block_no" in keys else None,
                "datetime": block_ts,
            }
        else:
            delta = None
            if address is not None:
                delta = 0
                for i, tx_input in enumerate(tx.inputs):
                    if i < len(inputs_addresses) and inputs_addresses[i] == address:
                        amt = await self.get_output_amount(
                            tx_input.tx_hash, tx_input.index)
                        delta -= amt or 0
                for o in tx.outputs:
                    if o.address == address:
                        delta += o.amount
                delta = coins(delta)
            inputs = []
            for i, tx_input in enumerate(tx.inputs):
                amt = await self.get_output_amount(
                    tx_input.tx_hash, tx_input.index)
                inputs.append({
                    "index": tx_input.index,
                    "tx_hash": tx_input.tx_hash,
                    "address": (inputs_addresses[i]
                                if i < len(inputs_addresses) else None),
                    "amount": coins(amt or 0),
                })
            out = {
                "is_coinbase": False, "hash": r["tx_hash"],
                "block_hash": r["block_hash"] if "block_hash" in keys else None,
                "block_no": r["block_no"] if "block_no" in keys else None,
                "datetime": block_ts,
                "message": tx.message.hex() if tx.message is not None else None,
                "transaction_type": tx.transaction_type.name,
                "is_confirm": is_confirm,
                "inputs": inputs,
                "delta": delta,
                "fees": coins(await self.tx_fees(tx)),
            }
        out["outputs"] = [
            {"address": o.address, "amount": coins(o.amount),
             "type": o.output_type.name}
            for o in tx.outputs
        ]
        return out

    async def get_block_transaction_hashes(self, block_hash: str) -> List[str]:
        rows = await self.drv.afetch(
            "SELECT tx_hash FROM transactions WHERE block_hash = $1",
            (block_hash,))
        if not rows and self.archive is not None:
            atxs = await self.archive.txs_for_block(block_hash)
            if atxs:
                return [t[1] for t in atxs]
        return [r["tx_hash"] for r in rows]

    async def get_address_pending_transactions(self, address: str) -> List[Tx]:
        rows = await self.drv.afetch(
            "SELECT tx_hex, inputs_addresses FROM pending_transactions")
        out = []
        for r in rows:
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            if address in list(r["inputs_addresses"]) or \
                    any(o.address == address for o in tx.outputs):
                out.append(tx)
        return out

    async def get_address_pending_spent_outpoints(
            self, address: str) -> List[Tuple[str, int]]:
        rows = await self.drv.afetch(
            "SELECT tx_hex, inputs_addresses FROM pending_transactions")
        out = []
        for r in rows:
            addrs = list(r["inputs_addresses"])
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            for i, tx_input in enumerate(tx.inputs):
                if i < len(addrs) and addrs[i] == address:
                    out.append((tx_input.tx_hash, tx_input.index))
        return out

    # ----------------------------------------------------------- rebuild --

    async def rebuild_utxos(self) -> None:
        """Full-chain replay of every output table from the transactions
        log (reference create_unspent_outputs.py + database.py:846-862)."""
        async with self._txn():
            for table in ("unspent_outputs",) + _GOV_TABLES:
                await self.drv.aexecute(f"DELETE FROM {table}")
            rows = await self.drv.afetch(
                "SELECT t.tx_hex FROM transactions t JOIN blocks b ON"
                " b.hash = t.block_hash ORDER BY b.id")
            txs = [tx_from_hex(r["tx_hex"], check_signatures=False)
                   for r in rows]
            for tx in txs:
                await self.add_transaction_outputs([tx])
                await self.remove_outputs([tx])
        if not self._owns_txn():
            # inside a replay transaction the owning scope resyncs the
            # index after its rollback; here, resync under the writer
            # lock so a concurrent commit can't be clobbered by a stale
            # snapshot swap
            async with self._writer():
                await self._aindex_rebuild()

    # ---------------------------------------------------------- snapshots --
    # Canonical positional row shapes shared with the sqlite backend
    # (docs/SNAPSHOT.md).  This schema has no amount columns on the
    # UTXO tables — amounts travel in the canonical rows anyway (joined
    # from transactions on export, dropped on restore) so one payload
    # restores on either backend.

    async def export_snapshot_rows(self, table: str) -> List[list]:
        if table not in ("unspent_outputs",) + _GOV_TABLES:
            raise ValueError(f"not a snapshot table: {table}")
        if table == "unspent_outputs":
            rows = await self.drv.afetch(
                'SELECT u.tx_hash, u."index", u.address, u.is_stake,'
                " t.outputs_amounts FROM unspent_outputs u"
                " JOIN transactions t ON t.tx_hash = u.tx_hash"
                ' ORDER BY u.tx_hash, u."index"')
            out = []
            for r in rows:
                amounts = list(r["outputs_amounts"] or [])
                idx = r["index"]
                out.append([r["tx_hash"], idx, r["address"],
                            int(amounts[idx]) if idx < len(amounts) else 0,
                            int(bool(r["is_stake"]))])
            return out
        rows = await self.drv.afetch(
            f'SELECT g.tx_hash, g."index", g.address, t.outputs_amounts'
            f" FROM {table} g JOIN transactions t ON t.tx_hash = g.tx_hash"
            ' ORDER BY g.tx_hash, g."index"')
        out = []
        for r in rows:
            amounts = list(r["outputs_amounts"] or [])
            idx = r["index"]
            out.append([r["tx_hash"], idx, r["address"],
                        int(amounts[idx]) if idx < len(amounts) else 0])
        return out

    async def export_snapshot_txs(self, tail: int) -> List[list]:
        """Witness transactions (see the sqlite twin): every tx still
        referenced by an exported outpoint plus the block tail's txs."""
        union = " UNION ".join(
            f"SELECT tx_hash FROM {t}"
            for t in ("unspent_outputs",) + _GOV_TABLES)
        rows = await self.drv.afetch(
            "SELECT block_hash, tx_hash, tx_hex, inputs_addresses,"
            " outputs_addresses, outputs_amounts, fees FROM transactions"
            f" WHERE tx_hash IN ({union}) OR block_hash IN"
            " (SELECT hash FROM blocks ORDER BY id DESC LIMIT $1)"
            " ORDER BY tx_hash", (tail,))
        return [[r["block_hash"], r["tx_hash"], r["tx_hex"],
                 list(r["inputs_addresses"] or []),
                 list(r["outputs_addresses"] or []),
                 [int(a) for a in (r["outputs_amounts"] or [])],
                 _units(r["fees"])] for r in rows]

    async def export_snapshot_blocks(self, tail: int) -> List[list]:
        rows = await self.drv.afetch(
            "SELECT id, hash, content, address, random, difficulty,"
            " reward, timestamp FROM blocks ORDER BY id DESC LIMIT $1",
            (tail,))
        return [[r["id"], r["hash"], r["content"], r["address"],
                 r["random"], str(r["difficulty"]), _units(r["reward"]),
                 _epoch(r["timestamp"])] for r in reversed(rows)]

    async def restore_snapshot(self, tables: Dict[str, List[list]],
                               txs: List[list], blocks: List[list]) -> None:
        """Wholesale replace of chain state with verified snapshot rows
        (one transaction; see the sqlite twin for the contract).
        Witness txs from blocks older than the carried tail dangle
        their block_hash foreign key, so on real PostgreSQL the restore
        runs under ``session_replication_role = replica`` (needs a
        superuser/owner role); the SET is best-effort because the
        sqlite-backed mock driver cannot parse it."""
        for name in tables:
            if name not in ("unspent_outputs",) + _GOV_TABLES:
                raise ValueError(f"not a snapshot table: {name}")
        async with self.atomic():
            try:
                await self.drv.aexecute(
                    "SET session_replication_role = replica")
            except Exception as e:
                log.debug("replica role unavailable (%s); witness-tx "
                          "FKs must hold on their own", e)
            for table in ("unspent_outputs",) + _GOV_TABLES:
                await self.drv.aexecute(f"DELETE FROM {table}")
            for table in ("pending_spent_outputs", "pending_transactions",
                          "transactions", "blocks"):
                await self.drv.aexecute(f"DELETE FROM {table}")
            await self.drv.aexecutemany(
                "INSERT INTO blocks (id, hash, content, address, random,"
                " difficulty, reward, timestamp)"
                " VALUES ($1,$2,$3,$4,$5,$6,$7,$8)",
                [(r[0], r[1], r[2], r[3], r[4], Decimal(r[5]),
                  _coins(r[6]), _utc(r[7])) for r in blocks])
            await self.drv.aexecutemany(
                "INSERT INTO transactions (block_hash, tx_hash, tx_hex,"
                " inputs_addresses, outputs_addresses, outputs_amounts,"
                " fees) VALUES ($1,$2,$3,$4,$5,$6,$7)",
                [(r[0], r[1], r[2], list(r[3]), list(r[4]),
                  [int(a) for a in r[5]], _coins(r[6])) for r in txs])
            await self.drv.aexecutemany(
                'INSERT INTO unspent_outputs (tx_hash, "index", address,'
                " is_stake) VALUES ($1,$2,$3,$4)",
                [(r[0], r[1], r[2], bool(r[4]))
                 for r in tables.get("unspent_outputs", [])])
            for table in _GOV_TABLES:
                await self.drv.aexecutemany(
                    f'INSERT INTO {table} (tx_hash, "index", address)'
                    " VALUES ($1,$2,$3)",
                    [(r[0], r[1], r[2]) for r in tables.get(table, [])])
            try:
                await self.drv.aexecute(
                    "SET session_replication_role = DEFAULT")
            except Exception as e:
                log.debug("could not reset replication role: %s", e)
        self._bump_fees_gen()
        async with self._writer():
            await self._aindex_rebuild()

    # ------------------------------------------------------------- archive --
    # Compactor seam (upow_tpu/archive/compactor.py, docs/ARCHIVE.md);
    # same contract as the sqlite twin.

    async def archive_export_span(self, lo: int, hi: int):
        """Canonical rows for heights [lo, hi]: (block rows ascending,
        {block_hash: [tx rows in acceptance order]}).  Within-block tx
        order relies on insertion order, the same assumption
        get_block_transactions already makes on this schema."""
        rows = await self.drv.afetch(
            "SELECT id, hash, content, address, random, difficulty,"
            " reward, timestamp FROM blocks WHERE id BETWEEN $1 AND $2"
            " ORDER BY id", (lo, hi))
        blocks = [[r["id"], r["hash"], r["content"], r["address"],
                   r["random"], str(r["difficulty"]), _units(r["reward"]),
                   _epoch(r["timestamp"])] for r in rows]
        txs_by_block: Dict[str, list] = {}
        if blocks:
            txs = await self.drv.afetch(
                "SELECT block_hash, tx_hash, tx_hex, inputs_addresses,"
                " outputs_addresses, outputs_amounts, fees FROM"
                " transactions WHERE block_hash = ANY($1)",
                ([b[1] for b in blocks],))
            for t in txs:
                txs_by_block.setdefault(t["block_hash"], []).append(
                    [t["block_hash"], t["tx_hash"], t["tx_hex"],
                     list(t["inputs_addresses"] or []),
                     list(t["outputs_addresses"] or []),
                     [int(a) for a in (t["outputs_amounts"] or [])],
                     _units(t["fees"])])
        return blocks, txs_by_block

    async def archive_prune_span(self, lo: int, hi: int) -> dict:
        """Delete hot blocks in [lo, hi] whose ENTIRE tx set is outside
        the snapshot witness closure, plus those blocks' txs (see the
        sqlite twin).  Doomed txs have no UTXO/governance references by
        construction, so the explicit deletes never trip a foreign
        key."""
        union = " UNION ".join(
            f"SELECT tx_hash FROM {t}"
            for t in ("unspent_outputs",) + _GOV_TABLES)
        async with self._txn():
            rows = await self.drv.afetch(
                "SELECT hash FROM blocks b WHERE b.id BETWEEN $1 AND $2"
                " AND NOT EXISTS (SELECT 1 FROM transactions t WHERE"
                f" t.block_hash = b.hash AND t.tx_hash IN ({union}))",
                (lo, hi))
            doomed = [r["hash"] for r in rows]
            n_txs = 0
            if doomed:
                counted = await self.drv.afetch(
                    "SELECT COUNT(*) AS n FROM transactions WHERE"
                    " block_hash = ANY($1)", (doomed,))
                n_txs = int(counted[0]["n"] or 0)
                await self.drv.aexecute(
                    "DELETE FROM transactions WHERE block_hash = ANY($1)",
                    (doomed,))
                await self.drv.aexecute(
                    "DELETE FROM blocks WHERE hash = ANY($1)", (doomed,))
            self._bump_fees_gen()  # memos may hold pruned-source rows
        return {"blocks": len(doomed), "txs": n_txs}

    async def archive_hot_row_counts(self) -> dict:
        b = await self.drv.afetch("SELECT COUNT(*) AS n FROM blocks")
        t = await self.drv.afetch("SELECT COUNT(*) AS n FROM transactions")
        return {"blocks": int(b[0]["n"] or 0), "txs": int(t[0]["n"] or 0)}


def _row_keys(r) -> set:
    """Column names of a driver row (asyncpg Record or mock dict)."""
    return set(r.keys())
