"""Chain state storage: the role of the reference's ``Database`` singleton.

The reference couples all chain state to asyncpg/PostgreSQL through an
~80-method ``Database`` class (database.py, 1654 LoC).  This framework
keeps the same *logical* schema (schema.sql: blocks, transactions, six
UTXO-class tables, pending tables) but:

* backs it with stdlib ``sqlite3`` (file or ``:memory:``) — a zero-dep,
  durable, transactional store; the storage API is the seam where a
  Postgres backend could be swapped in for reference interop,
* keeps amounts as **int smallest-units** end to end (the reference's
  NUMERIC/Decimal appears only in governance ratio math, which is
  Decimal-exact here too — core/rewards.py),
* avoids the reference's LIKE-'%hex%' address scans (database.py:864-937)
  by materializing an ``address`` column on outputs and a JSON address
  array on transactions,
* exposes the *state-view* callbacks the pure consensus kernel needs
  (core/tx.py ``AddressResolver``) instead of letting codecs import the
  database (the circular-import knot SURVEY.md §1 flags).

All methods are ``async def`` to slot into the asyncio node shell; sqlite
calls are short and synchronous under a process-wide connection with WAL.
Block acceptance is wrapped in one transaction (``atomic``) — the
serializable-retry loop the reference hand-rolls (database.py:640-672)
comes for free from sqlite's locking.
"""

from __future__ import annotations

import json
import os
import sqlite3
import time as _time
from contextlib import asynccontextmanager
from decimal import Decimal
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from ..core.clock import timestamp as now_ts
from ..core.codecs import OutputType, TransactionType
from ..core.constants import MAX_BLOCK_SIZE_HEX, SMALLEST
from ..core.rewards import round_up_decimal
from ..core.tx import CoinbaseTx, Tx, TxInput, tx_from_hex

AnyTx = Union[Tx, CoinbaseTx]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
    id INTEGER PRIMARY KEY,
    hash TEXT UNIQUE NOT NULL,
    content TEXT NOT NULL,
    address TEXT NOT NULL,
    random INTEGER NOT NULL,
    difficulty TEXT NOT NULL,
    reward INTEGER NOT NULL,
    timestamp INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS transactions (
    block_hash TEXT NOT NULL,
    tx_hash TEXT UNIQUE NOT NULL,
    tx_hex TEXT NOT NULL,
    inputs_addresses TEXT NOT NULL,
    outputs_addresses TEXT NOT NULL,
    outputs_amounts TEXT NOT NULL,
    fees INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS tx_block_hash_idx ON transactions (block_hash);
CREATE TABLE IF NOT EXISTS pending_transactions (
    tx_hash TEXT UNIQUE NOT NULL,
    tx_hex TEXT NOT NULL,
    inputs_addresses TEXT NOT NULL,
    fees INTEGER NOT NULL,
    propagation_time INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS pending_spent_outputs (
    tx_hash TEXT NOT NULL,
    idx INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS unspent_outputs (
    tx_hash TEXT NOT NULL,
    idx INTEGER NOT NULL,
    address TEXT,
    amount INTEGER NOT NULL,
    is_stake INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (tx_hash, idx)
);
CREATE INDEX IF NOT EXISTS unspent_address_idx ON unspent_outputs (address);
"""

# The five governance tables share one row shape (outpoint + address).
_GOV_TABLES = (
    "inode_registration_output",
    "validator_registration_output",
    "validators_voting_power",
    "delegates_voting_power",
    "inodes_ballot",
    "validators_ballot",
)

for _t in _GOV_TABLES:
    _SCHEMA += f"""
CREATE TABLE IF NOT EXISTS {_t} (
    tx_hash TEXT NOT NULL,
    idx INTEGER NOT NULL,
    address TEXT,
    amount INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (tx_hash, idx)
);
CREATE INDEX IF NOT EXISTS {_t}_address_idx ON {_t} (address);
"""

# OutputType -> table routing (reference database.py:524-580)
_OUTPUT_TABLE = {
    OutputType.REGULAR: "unspent_outputs",
    OutputType.STAKE: "unspent_outputs",
    OutputType.UN_STAKE: "unspent_outputs",
    OutputType.INODE_REGISTRATION: "inode_registration_output",
    OutputType.VALIDATOR_REGISTRATION: "validator_registration_output",
    OutputType.VALIDATOR_VOTING_POWER: "validators_voting_power",
    OutputType.DELEGATE_VOTING_POWER: "delegates_voting_power",
    OutputType.VOTE_AS_VALIDATOR: "inodes_ballot",
    OutputType.VOTE_AS_DELEGATE: "validators_ballot",
}

# TransactionType -> which table its *inputs* spend from
# (reference database.py:589-622 remove_outputs partitioning)
_INPUT_TABLE = {
    TransactionType.INODE_DE_REGISTRATION: "inode_registration_output",
    TransactionType.VOTE_AS_VALIDATOR: "validators_voting_power",
    TransactionType.VOTE_AS_DELEGATE: "delegates_voting_power",
    TransactionType.REVOKE_AS_VALIDATOR: "inodes_ballot",
    TransactionType.REVOKE_AS_DELEGATE: "validators_ballot",
}


from .views import StateViews


class ChainState(StateViews):
    """One chain's durable state.  ``path=None`` -> in-memory (tests).

    The backend-independent consensus views (balance/stake aggregation,
    the active-inode cascade, fee math, fingerprints) live in
    :class:`StateViews`; this class implements the sqlite storage
    primitives under them.  :class:`upow_tpu.state.pg.PgChainState` is
    the PostgreSQL implementation of the same seam."""

    def __init__(self, path: Optional[str] = None,
                 device_index: bool = False,
                 sole_writer: bool = True):
        self.path = path or ":memory:"
        # sole_writer=False (e.g. a wallet CLI reading a file the node is
        # writing) disables the 50 ms rate limit on the data_version
        # check: every memo read verifies no other connection committed,
        # so a secondary reader never serves stale amounts/addresses into
        # fee/coinbase computation.
        self.sole_writer = sole_writer
        self.db = sqlite3.connect(self.path)
        self.db.row_factory = sqlite3.Row
        if path:
            self.db.execute("PRAGMA journal_mode=WAL")
        self.db.execute("PRAGMA foreign_keys=OFF")
        self.db.executescript(_SCHEMA)
        self.db.commit()
        # emission audit sidecar (reference: emission_details.json pickledb)
        self.emission_path = (
            os.path.splitext(path)[0] + ".emission.json" if path else None
        )
        # optional device-resident membership prefilter per UTXO table
        # (SURVEY.md §2.2; the block-accept hot path's spend check)
        self._dev_index: Optional[Dict[str, object]] = None
        if device_index:
            self.enable_device_index()
        # decoded-mempool cache: several read paths walk every pending tx
        # (balance/stake with check_pending, builder guards); decoding the
        # whole mempool hex per call is the reference's O(mempool)
        # anti-pattern (database.py:1138-1205) — decode once per intake.
        self._pending_cache: Optional[Dict[str, Tx]] = None
        self._pending_stamp: tuple = (-1, -1, -1)
        self._pending_gen = 0  # bumped on every LOCAL mempool mutation
        # reorg mempool re-injection (mempool subsystem policy; the Node
        # turns it on from MempoolConfig — off at the library level so
        # state-only embedders keep the reference rollback semantics)
        self.reinject_reorg_txs = False
        # reorg notification (state/hotcache.py): called with the first
        # removed block id AFTER a remove_blocks rollback commits.  Sync
        # and swarm heal call remove_blocks directly on state, so the
        # read cache's generation hook has to live here rather than on
        # the BlockManager.
        self.on_blocks_removed = None
        # cold-block archive fallthrough (upow_tpu/archive/,
        # docs/ARCHIVE.md): the node attaches an ArchiveReader when
        # ArchiveConfig.dir is set; None keeps every read path exactly
        # on its hot-only query.
        self.archive = None
        from collections import OrderedDict as _OD

        self._amount_cache: "_OD[tuple, object]" = _OD()
        self._data_version = self._db_data_version()
        self._data_version_checked = 0.0

    def _db_data_version(self) -> int:
        return self.db.execute("PRAGMA data_version").fetchone()[0]

    def _amount_cache_get(self, key):
        """Cached output amount/address, guarded against writes from
        OTHER connections on the same db file (the wallet CLI opens its
        own ChainState): sqlite's data_version counter bumps whenever a
        different connection commits, and any such commit may have
        deleted source txs — so the whole memo is dropped then.

        The version check is rate-limited to one PRAGMA per 50 ms — at
        ~25k lookups per 8k-tx block the per-hit pragma cost halved the
        warm accept rate.  The window only affects SECONDARY processes
        reading a file another process mutates (this connection's own
        deletions invalidate explicitly and see no window); those reads
        race ongoing commits by >=50 ms anyway.
        """
        now = _time.monotonic()
        if not self.sole_writer or now - self._data_version_checked >= 0.05:
            self._data_version_checked = now
            version = self._db_data_version()
            if version != self._data_version:
                self._data_version = version
                self._amount_cache.clear()
                return None
        return self._amount_cache.get(key)

    def _amount_cache_put(self, key, value) -> None:
        self._amount_cache[key] = value
        while len(self._amount_cache) > (1 << 16):
            self._amount_cache.popitem(last=False)

    def _amount_cache_drop(self, tx_hashes) -> None:
        """Forget cached output amounts for deleted txs (see
        get_output_amount: existence must not depend on cache warmth)."""
        gone = set(tx_hashes)
        if gone:
            for key in [k for k in self._amount_cache if k[0] in gone]:
                del self._amount_cache[key]

    async def _pending_decoded(self) -> Dict[str, Tx]:
        # (count, max rowid) detects writes from OTHER connections (the
        # wallet CLI's direct-mempool fallback shares the sqlite file):
        # inserts bump max rowid, deletes drop the count.  The local
        # generation counter covers the one combination they miss —
        # delete-the-newest-then-insert reuses the freed max rowid at an
        # unchanged count (sqlite rowid reuse without AUTOINCREMENT).
        r = self.db.execute(
            "SELECT COUNT(*) AS c, COALESCE(MAX(rowid), 0) AS m"
            " FROM pending_transactions").fetchone()
        stamp = (r["c"], r["m"], self._pending_gen)
        if self._pending_cache is None or self._pending_stamp != stamp:
            rows = self.db.execute(
                "SELECT tx_hash, tx_hex FROM pending_transactions").fetchall()
            self._pending_cache = {
                row["tx_hash"]: tx_from_hex(row["tx_hex"], check_signatures=False)
                for row in rows
            }
            self._pending_stamp = stamp
        return self._pending_cache

    # ------------------------------------------------------ device index --
    def enable_device_index(self) -> None:
        """Mirror every UTXO-class table into a :class:`DeviceUtxoIndex`.

        Maintained incrementally by the output add/remove paths; bulk
        operations (reorg rollback, full replay) rebuild from the tables
        — the index is reconstructible at any height, which is its
        checkpoint/resume story.

        No-op (with a warning) when the jax backend cannot initialize —
        a dead TPU tunnel HANGS backend init, and a node must boot and
        validate on the sqlite path rather than wedge here."""
        from ..benchutil import probed_platform_cached

        if probed_platform_cached(timeout=90.0) is None:
            import logging

            logging.getLogger("upow_tpu.state").warning(
                "jax backend init hung/failed; device UTXO index disabled "
                "— sqlite membership checks only")
            self._dev_index = None
            return
        from .device_index import DeviceUtxoIndex

        self._dev_index = {}
        for table in ("unspent_outputs",) + _GOV_TABLES:
            rows = self.db.execute(
                f"SELECT tx_hash, idx, amount, address FROM {table}"
            ).fetchall()
            self._dev_index[table] = DeviceUtxoIndex(
                [(r["tx_hash"], r["idx"]) for r in rows],
                values=[(r["amount"], r["address"] or "", 0) for r in rows])

    def _index_add(self, table: str, outpoints, values=None) -> None:
        if self._dev_index is not None:
            self._dev_index[table].add(outpoints, values)

    def _index_remove(self, table: str, outpoints) -> None:
        if self._dev_index is not None:
            self._dev_index[table].remove(outpoints)

    def _index_rebuild(self) -> None:
        if self._dev_index is not None:
            self.enable_device_index()

    def resident_indexes(self) -> Optional[Dict[str, object]]:
        """The per-table :class:`DeviceUtxoIndex` map when the device
        index is enabled and armed, else None — the accept path's gate
        for the fused resident probe (verify/block.py)."""
        return self._dev_index

    def index_stats(self) -> Optional[dict]:
        """Aggregate resident-index telemetry across every UTXO-class
        table (residency bytes, probe/shadow-consult counters) for the
        /metrics exporter; None when the index is disabled."""
        if not self._dev_index:
            return None
        agg = {"entries": 0, "resident_bytes": 0, "probes": 0,
               "shadow_consults": 0, "twin_fingerprints": 0}
        for index in self._dev_index.values():
            s = index.stats()
            for k in agg:
                agg[k] += s[k]
        return agg

    def close(self):
        self.db.close()

    @asynccontextmanager
    async def atomic(self):
        """One transaction around a whole block acceptance.  While it is
        open, the per-method ``_commit()`` calls inside are no-ops — a
        partial block must never become durable (an inner commit would
        make atomic()'s rollback silently keep the committed half:
        accepted block + mempool removals with the spent UTXOs still
        unspent)."""
        self._in_atomic = True
        try:
            self.db.execute("BEGIN")
            yield
            self.db.commit()
        except BaseException:
            self.db.rollback()
            self._amount_cache.clear()  # may hold rolled-back rows
            self._bump_fees_gen()
            self._index_rebuild()  # undo any index updates the txn made
            raise
        finally:
            self._in_atomic = False

    def _commit(self) -> None:
        if not getattr(self, "_in_atomic", False):
            self.db.commit()

    # ------------------------------------------------------------- blocks --

    async def add_block(self, block_id: int, block_hash: str, content: str,
                        address: str, nonce: int, difficulty, reward: int,
                        ts: int) -> None:
        self.db.execute(
            "INSERT INTO blocks (id, hash, content, address, random, difficulty,"
            " reward, timestamp) VALUES (?,?,?,?,?,?,?,?)",
            (block_id, block_hash, content, address, nonce, str(difficulty),
             reward, ts),
        )

    @staticmethod
    def _block_dict(r) -> dict:
        return {
            "id": r["id"],
            "hash": r["hash"],
            "content": r["content"],
            "address": r["address"],
            "random": r["random"],
            "difficulty": Decimal(r["difficulty"]),
            "reward": Decimal(r["reward"]) / SMALLEST,
            "timestamp": r["timestamp"],
        }

    @staticmethod
    def _archive_block_dict(b: list) -> dict:
        """Canonical archive block row -> the same dict _block_dict
        builds from a hot row (difficulty is archived as str, reward as
        int smallest-units — identical to the hot column encodings)."""
        return {
            "id": b[0],
            "hash": b[1],
            "content": b[2],
            "address": b[3],
            "random": b[4],
            "difficulty": Decimal(b[5]),
            "reward": Decimal(b[6]) / SMALLEST,
            "timestamp": b[7],
        }

    async def get_block(self, block_hash: str) -> Optional[dict]:
        r = self.db.execute("SELECT * FROM blocks WHERE hash = ?", (block_hash,)).fetchone()
        if r is None and self.archive is not None:
            b = await self.archive.block_by_hash(block_hash)
            return self._archive_block_dict(b) if b else None
        return self._block_dict(r) if r else None

    async def get_block_by_id(self, block_id: int) -> Optional[dict]:
        r = self.db.execute("SELECT * FROM blocks WHERE id = ?", (block_id,)).fetchone()
        if r is None and self.archive is not None:
            b = await self.archive.block_by_height(block_id)
            return self._archive_block_dict(b) if b else None
        return self._block_dict(r) if r else None

    async def get_last_block(self) -> Optional[dict]:
        r = self.db.execute("SELECT * FROM blocks ORDER BY id DESC LIMIT 1").fetchone()
        return self._block_dict(r) if r else None

    async def get_next_block_id(self) -> int:
        r = self.db.execute("SELECT MAX(id) AS m FROM blocks").fetchone()
        return (r["m"] or 0) + 1

    async def get_blocks(self, offset: int, limit: int,
                         tx_details: bool = False,
                         size_capped: bool = False) -> List[dict]:
        """Blocks with embedded full transactions, ordered by id
        (reference database.py:380-408's get_blocks).

        One transactions query for the whole page, grouped host-side —
        a couple of statements per 500-block page instead of 501 (same
        shape as the pg backend's; ``tx_details`` swaps the tx hex for
        explorer-shaped dicts at the reference's per-tx lookup cost,
        database.py:405).  ``size_capped`` truncates the running page
        once the accumulated hex passes 8 full blocks' worth — the HTTP
        serving layer passes it (a 1000-block page of 2 MB blocks must
        not serialize a 2 GB response).  Documented divergence: the
        reference caps INSIDE Database.get_blocks unconditionally,
        which silently truncates its own reorg-window scan; we cap only
        at the wire boundary so internal callers always see the full
        window (and the reorg scan pairs blocks by id, app.py)."""
        rows = self.db.execute(
            "SELECT * FROM blocks WHERE id >= ? ORDER BY id LIMIT ?",
            (offset, limit),
        ).fetchall()
        by_hash: dict = {r["hash"]: [] for r in rows}
        hashes = list(by_hash)
        # chunk the IN list: SQLITE_MAX_VARIABLE_NUMBER is 999 before
        # sqlite 3.32, and the endpoint serves pages up to 1000 blocks
        for lo in range(0, len(hashes), 900):
            chunk = hashes[lo:lo + 900]
            marks = ",".join("?" * len(chunk))
            for t in self.db.execute(
                    f"SELECT block_hash, tx_hash, tx_hex FROM transactions"
                    f" WHERE block_hash IN ({marks})", chunk):
                by_hash[t["block_hash"]].append((t["tx_hash"], t["tx_hex"]))
        entries = [(r["id"], self._block_dict(r), by_hash[r["hash"]])
                   for r in rows]
        if self.archive is not None:
            cov = await self.archive.coverage()
            if cov is not None and offset <= cov[1]:
                # the page reaches into the archived span: overlay
                # archived blocks (hot wins on overlap — same content
                # either way; witness blocks stay hot below the archive
                # horizon, so hot gaps can appear anywhere in the page)
                hot_ids = {e[0] for e in entries}
                for b, atxs in await self.archive.span(
                        offset, offset + limit - 1):
                    if b[0] not in hot_ids:
                        entries.append((b[0], self._archive_block_dict(b),
                                        [(t[1], t[2]) for t in atxs]))
                entries.sort(key=lambda e: e[0])
                entries = entries[:limit]
        out = []
        size = 0
        for _bid, block, txs in entries:
            size += sum(len(h) for _th, h in txs)
            if size_capped and size > MAX_BLOCK_SIZE_HEX * 8:
                break
            block = dict(block)
            block["difficulty"] = float(block["difficulty"])
            block["reward"] = str(block["reward"])
            if tx_details:
                # per-tx lookups are inherent to the explorer shape
                # (fees + per-input amounts need resolution; the
                # reference pays the same, database.py:405).  A tx can
                # vanish mid-page under a concurrent reorg — drop the
                # None instead of embedding null in the response.
                nice = [await self.get_nice_transaction(th)
                        for th, _h in txs]
                tx_list = [t for t in nice if t is not None]
            else:
                tx_list = [h for _th, h in txs]
            out.append({"block": block, "transactions": tx_list})
        return out

    async def remove_blocks(self, from_block_id: int) -> None:
        """Reorg rollback: restore outputs spent by the removed blocks, drop
        the blocks and everything their transactions created
        (reference database.py:146-169)."""
        rows = self.db.execute(
            "SELECT t.tx_hex FROM transactions t JOIN blocks b ON t.block_hash = b.hash"
            " WHERE b.id >= ?", (from_block_id,),
        ).fetchall()
        txs = [tx_from_hex(r["tx_hex"], check_signatures=False) for r in rows]
        from .. import trace

        trace.event("reorg", from_block=from_block_id,
                    removed_txs=len(txs))
        # drop outputs created by removed txs (from whichever table)
        created = [tx.hash() for tx in txs]
        for table in ("unspent_outputs",) + _GOV_TABLES:
            self.db.executemany(
                f"DELETE FROM {table} WHERE tx_hash = ?", [(h,) for h in created]
            )
        # O(delta) index maintenance (ISSUE 11): enumerate the removed
        # txs' outputs by class and delta-remove them — already-spent
        # outputs are absent and no-op, matching the blanket SQL DELETE.
        # The restored spends below delta-add through the same hooks, so
        # the full rebuild a reorg used to pay is gone.
        if self._dev_index is not None:
            doomed_by_table: Dict[str, list] = {}
            for tx in txs:
                h = tx.hash()
                for index, out in enumerate(tx.outputs):
                    doomed_by_table.setdefault(
                        _OUTPUT_TABLE[out.output_type], []).append((h, index))
            for table, outpoints in doomed_by_table.items():
                self._index_remove(table, outpoints)
        # restore outputs their inputs had spent — but not outputs of txs
        # that are themselves being removed (reference database.py
        # remove_blocks filters `tx_input.tx_hash not in transactions_hashes`;
        # restoring those would leave orphaned UTXO rows after a reorg of
        # dependent txs and diverge the UTXO fingerprint)
        created_set = set(created)
        restore = [
            tx_input for tx in txs if not tx.is_coinbase
            for tx_input in tx.inputs if tx_input.tx_hash not in created_set
        ]
        await self._restore_spent_outputs(restore)
        self.db.executemany(
            "DELETE FROM transactions WHERE tx_hash = ?", [(h,) for h in created]
        )
        self.db.execute("DELETE FROM blocks WHERE id >= ?", (from_block_id,))
        self._amount_cache_drop(created)
        if self.reinject_reorg_txs:
            # mempool re-injection: txs the losing fork confirmed go
            # back into the pending journal (their spent outputs were
            # just restored above) so the winning fork can mine them
            # instead of silently dropping user transactions.  Skips
            # txs that spend an output of another removed tx (source
            # gone) or conflict with the existing pending overlay.
            for tx in txs:
                if tx.is_coinbase or any(
                        i.tx_hash in created_set for i in tx.inputs):
                    continue
                await self._reinject_pending(tx)
        self._bump_fees_gen()
        self._pending_gen += 1
        self._commit()
        if self.on_blocks_removed is not None:
            self.on_blocks_removed(from_block_id)

    async def _reinject_pending(self, tx) -> bool:
        """INSERT-OR-IGNORE a reorged-out tx back into the journal.
        Returns True when the row (and its spent-output overlay rows)
        actually landed."""
        outpoints = [i.outpoint for i in tx.inputs]
        if await self.get_pending_spent_outpoints(outpoints):
            return False  # conflicts with a live pending tx
        try:
            inputs_addresses = [
                await self.resolve_output_address(i.tx_hash, i.index) or ""
                for i in tx.inputs
            ]
            fees = await self.tx_fees(tx)
        except (ValueError, KeyError, IndexError):
            return False  # source txs unresolvable post-rollback
        cur = self.db.execute(
            "INSERT OR IGNORE INTO pending_transactions (tx_hash, tx_hex,"
            " inputs_addresses, fees, propagation_time) VALUES (?,?,?,?,?)",
            (tx.hash(), tx.hex(), json.dumps(inputs_addresses), fees,
             now_ts()),
        )
        if cur.rowcount == 0:
            return False  # already pending (re-propagated meanwhile)
        self.db.executemany(
            "INSERT INTO pending_spent_outputs (tx_hash, idx) VALUES (?,?)",
            [(i.tx_hash, i.index) for i in tx.inputs],
        )
        from .. import trace

        trace.inc("mempool.reinjected")
        return True

    async def _restore_spent_outputs(self, inputs: List[TxInput]) -> None:
        """Re-materialize spent outputs by decoding their source txs.
        Index delta-adds are gated on the INSERT actually landing
        (OR IGNORE may hit an existing row, e.g. a whitelisted
        historical double-spend restoring one outpoint twice) so the
        resident index never drifts a duplicate ahead of the table."""
        for tx_input in inputs:
            src = await self.get_transaction(tx_input.tx_hash, include_pending=False)
            if src is None:
                continue
            out = src.outputs[tx_input.index]
            table = _OUTPUT_TABLE[out.output_type]
            if table == "unspent_outputs":
                cur = self.db.execute(
                    "INSERT OR IGNORE INTO unspent_outputs (tx_hash, idx, address,"
                    " amount, is_stake) VALUES (?,?,?,?,?)",
                    (tx_input.tx_hash, tx_input.index, out.address, out.amount,
                     int(out.is_stake)),
                )
            else:
                cur = self.db.execute(
                    f"INSERT OR IGNORE INTO {table} (tx_hash, idx, address, amount)"
                    " VALUES (?,?,?,?)",
                    (tx_input.tx_hash, tx_input.index, out.address, out.amount),
                )
            if cur.rowcount > 0:
                self._index_add(table, [(tx_input.tx_hash, tx_input.index)],
                                values=[(out.amount, out.address or "", 0)])

    # ------------------------------------------------------- transactions --

    async def add_transactions(self, txs: Sequence[AnyTx], block_hash: str) -> None:
        rows = []
        for tx in txs:
            inputs_addresses = [] if tx.is_coinbase else [
                await self.resolve_output_address(i.tx_hash, i.index) or ""
                for i in tx.inputs
            ]
            fees = 0 if tx.is_coinbase else await self.tx_fees(tx)
            rows.append((
                block_hash, tx.hash(), tx.hex(),
                json.dumps(inputs_addresses),
                json.dumps([o.address for o in tx.outputs]),
                json.dumps([o.amount for o in tx.outputs]),
                fees,
            ))
        self.db.executemany(
            "INSERT OR REPLACE INTO transactions (block_hash, tx_hash, tx_hex,"
            " inputs_addresses, outputs_addresses, outputs_amounts, fees)"
            " VALUES (?,?,?,?,?,?,?)", rows,
        )

    async def get_transaction(self, tx_hash: str,
                              include_pending: bool = False) -> Optional[AnyTx]:
        r = self.db.execute(
            "SELECT tx_hex FROM transactions WHERE tx_hash = ?", (tx_hash,)
        ).fetchone()
        if r is None and include_pending:
            r = self.db.execute(
                "SELECT tx_hex FROM pending_transactions WHERE tx_hash = ?",
                (tx_hash,),
            ).fetchone()
        if r is None and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                return tx_from_hex(hit[0][2], check_signatures=False)
        return tx_from_hex(r["tx_hex"], check_signatures=False) if r else None

    async def get_transaction_info(self, tx_hash: str) -> Optional[dict]:
        r = self.db.execute(
            "SELECT * FROM transactions WHERE tx_hash = ?", (tx_hash,)
        ).fetchone()
        if r is None:
            if self.archive is not None:
                hit = await self.archive.tx_by_hash(tx_hash)
                if hit is not None:
                    t = hit[0]
                    return {
                        "block_hash": t[0], "tx_hash": t[1],
                        "tx_hex": t[2], "inputs_addresses": t[3],
                        "outputs_addresses": t[4],
                        "outputs_amounts": t[5], "fees": t[6],
                    }
            return None
        return {
            "block_hash": r["block_hash"],
            "tx_hash": r["tx_hash"],
            "tx_hex": r["tx_hex"],
            "inputs_addresses": json.loads(r["inputs_addresses"]),
            "outputs_addresses": json.loads(r["outputs_addresses"]),
            "outputs_amounts": json.loads(r["outputs_amounts"]),
            "fees": r["fees"],
        }

    async def get_block_transactions(self, block_hash: str,
                                     hex_only: bool = False) -> List:
        rows = self.db.execute(
            "SELECT tx_hex FROM transactions WHERE block_hash = ?", (block_hash,)
        ).fetchall()
        if not rows and self.archive is not None:
            # pruned blocks lose their ENTIRE tx set (never split), so
            # an empty hot read is the only case needing fallthrough
            atxs = await self.archive.txs_for_block(block_hash)
            if atxs:
                if hex_only:
                    return [t[2] for t in atxs]
                return [tx_from_hex(t[2], check_signatures=False)
                        for t in atxs]
        if hex_only:
            return [r["tx_hex"] for r in rows]
        return [tx_from_hex(r["tx_hex"], check_signatures=False) for r in rows]

    async def resolve_output_address(self, tx_hash: str, index: int) -> Optional[str]:
        """AddressResolver for the codec's ambiguous-signature relink
        (core/tx.py tx_from_hex).  Memoized with the same
        content-addressed + dropped-on-tx-deletion discipline as
        :func:`get_output_amount` (shared cache, misses not cached)."""
        key = (tx_hash, -1 - index)  # distinct key space from amounts
        addr = self._amount_cache_get(key)
        if addr is not None:
            return addr
        r = self.db.execute(
            "SELECT outputs_addresses FROM transactions WHERE tx_hash = ?",
            (tx_hash,),
        ).fetchone()
        if r is None:
            r = self.db.execute(
                "SELECT tx_hex FROM pending_transactions WHERE tx_hash = ?",
                (tx_hash,),
            ).fetchone()
            if r is None:
                if self.archive is not None:
                    hit = await self.archive.tx_by_hash(tx_hash)
                    if hit is not None:
                        addresses = hit[0][4]
                        addr = (addresses[index]
                                if index < len(addresses) else None)
                        if addr is not None:
                            self._amount_cache_put(key, addr)
                        return addr
                return None
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            addr = (tx.outputs[index].address
                    if index < len(tx.outputs) else None)
        else:
            addresses = json.loads(r["outputs_addresses"])
            addr = addresses[index] if index < len(addresses) else None
        if addr is not None:
            self._amount_cache_put(key, addr)
        return addr

    async def get_output_amount(self, tx_hash: str, index: int) -> Optional[int]:
        # content-addressed (tx_hash = sha256(full tx hex), so a hash's
        # outputs can never change), but existence matters: tx_fees
        # returns 0 when the source tx is GONE, and that decision must
        # not depend on cache warmth (consensus-adjacent — it feeds the
        # coinbase miner_amount).  Every path that deletes txs
        # (remove_blocks, pending removals) drops the affected entries.
        key = (tx_hash, index)
        amount = self._amount_cache_get(key)
        if amount is not None:
            return amount
        r = self.db.execute(
            "SELECT outputs_amounts FROM transactions WHERE tx_hash = ?",
            (tx_hash,),
        ).fetchone()
        if r is not None:
            amounts = json.loads(r["outputs_amounts"])
            amount = amounts[index] if index < len(amounts) else None
        else:
            r = self.db.execute(
                "SELECT tx_hex FROM pending_transactions WHERE tx_hash = ?",
                (tx_hash,),
            ).fetchone()
            if r is None:
                if self.archive is not None:
                    hit = await self.archive.tx_by_hash(tx_hash)
                    if hit is not None:
                        amounts = hit[0][5]
                        amount = (amounts[index]
                                  if index < len(amounts) else None)
                        if amount is not None:
                            self._amount_cache_put(key, amount)
                        return amount
                return None
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            amount = (tx.outputs[index].amount
                      if index < len(tx.outputs) else None)
        if amount is not None:
            self._amount_cache_put(key, amount)
        return amount

    # ------------------------------------------------------------ mempool --

    async def add_pending_transaction(self, tx: Tx) -> int:
        """Insert one journal row; returns its journal sequence (the
        sqlite rowid) — with no interleaved foreign writer, the stamp's
        MAX(rowid) after this call equals the returned value, which is
        what lets the mempool intake predict the stamp its own batch
        should produce (Mempool.reconcile)."""
        inputs_addresses = [
            await self.resolve_output_address(i.tx_hash, i.index) or ""
            for i in tx.inputs
        ]
        fees = await self.tx_fees(tx)
        cur = self.db.execute(
            "INSERT INTO pending_transactions (tx_hash, tx_hex, inputs_addresses,"
            " fees, propagation_time) VALUES (?,?,?,?,?)",
            (tx.hash(), tx.hex(), json.dumps(inputs_addresses), fees, now_ts()),
        )
        seq = cur.lastrowid
        self.db.executemany(
            "INSERT INTO pending_spent_outputs (tx_hash, idx) VALUES (?,?)",
            [(i.tx_hash, i.index) for i in tx.inputs],
        )
        self._commit()
        self._pending_gen += 1
        return seq

    async def pending_transaction_exists(self, tx_hash: str) -> bool:
        r = self.db.execute(
            "SELECT 1 FROM pending_transactions WHERE tx_hash = ?", (tx_hash,)
        ).fetchone()
        return r is not None

    async def get_pending_transactions_limit(
        self, limit_hex_chars: int = 4096 * 1024, hex_only: bool = False
    ) -> List:
        """Fee-rate-ordered mempool slice capped by total hex size
        (reference database.py:171-186 ORDER BY fees/LENGTH(tx_hex) DESC,
        cap MAX_BLOCK_SIZE_HEX)."""
        rows = self.db.execute(
            "SELECT tx_hex FROM pending_transactions ORDER BY"
            " CAST(fees AS REAL)/LENGTH(tx_hex) DESC, tx_hash"
        ).fetchall()
        out, total = [], 0
        for r in rows:
            if total + len(r["tx_hex"]) > limit_hex_chars:
                break
            total += len(r["tx_hex"])
            out.append(r["tx_hex"])
        if hex_only:
            return out
        return [tx_from_hex(h, check_signatures=False) for h in out]

    async def get_pending_transactions_by_hash(self, hashes: List[str]) -> List[str]:
        """Batched: chunked ``IN (...)`` like the removal path instead of
        one SELECT per hash (push_block resolves up to a whole block's
        txs through here).  Found hexes come back in request order."""
        found: Dict[str, str] = {}
        for i in range(0, len(hashes), 500):
            chunk = hashes[i:i + 500]
            ph = ",".join("?" * len(chunk))
            for r in self.db.execute(
                    "SELECT tx_hash, tx_hex FROM pending_transactions"
                    f" WHERE tx_hash IN ({ph})", chunk):
                found[r["tx_hash"]] = r["tx_hex"]
        return [found[h] for h in hashes if h in found]

    async def get_pending_spent_outpoints(self, outpoints=None) -> set:
        """Pending-spent overlay; with ``outpoints`` only the matching
        subset is fetched (the reference's get_pending_spent_outputs
        filters the same way, database.py:126-133 caller) — intake
        checks one tx's inputs, and a full-overlay scan per incoming tx
        is quadratic in mempool depth (profiled: 28% of push_tx)."""
        if outpoints is None:
            rows = self.db.execute(
                "SELECT tx_hash, idx FROM pending_spent_outputs").fetchall()
            return {(r["tx_hash"], r["idx"]) for r in rows}
        want = {tuple(o) for o in outpoints}
        if not want:
            return set()
        hashes = list({h for h, _ in want})
        marks = ",".join("?" * len(hashes))
        rows = self.db.execute(
            f"SELECT tx_hash, idx FROM pending_spent_outputs"
            f" WHERE tx_hash IN ({marks})", hashes).fetchall()
        return {(r["tx_hash"], r["idx"]) for r in rows} & want

    async def remove_pending_transactions_by_hash(self, hashes: List[str]) -> None:
        """Batched (8k-tx block profile): the spent-output overlay rows
        only ever exist alongside a live pending_transactions row (see
        add_pending_transaction), so one SELECT per chunk over the
        pending table finds every tx whose overlay needs cleanup — no
        per-hash lookup, no re-parsing just-accepted txs out of the
        transactions table."""
        to_drop: List[str] = []
        for i in range(0, len(hashes), 500):
            chunk = hashes[i:i + 500]
            ph = ",".join("?" * len(chunk))
            rows = self.db.execute(
                "SELECT tx_hex FROM pending_transactions"
                f" WHERE tx_hash IN ({ph})", chunk).fetchall()
            spent = []
            for r in rows:
                tx = tx_from_hex(r["tx_hex"], check_signatures=False)
                if not tx.is_coinbase:
                    spent.extend((inp.tx_hash, inp.index) for inp in tx.inputs)
            if spent:
                self.db.executemany(
                    "DELETE FROM pending_spent_outputs"
                    " WHERE tx_hash = ? AND idx = ?", spent)
            self.db.execute(
                f"DELETE FROM pending_transactions WHERE tx_hash IN ({ph})",
                chunk)
            confirmed = {r["tx_hash"] for r in self.db.execute(
                f"SELECT tx_hash FROM transactions WHERE tx_hash IN ({ph})",
                chunk).fetchall()}
            to_drop.extend(h for h in chunk if h not in confirmed)
        self._amount_cache_drop(to_drop)
        self._commit()
        self._pending_gen += 1

    async def remove_pending_transactions(self) -> None:
        self.db.execute("DELETE FROM pending_transactions")
        self.db.execute("DELETE FROM pending_spent_outputs")
        self._amount_cache.clear()
        self._commit()
        self._pending_gen += 1

    async def get_pending_transactions_count(self) -> int:
        return self.db.execute(
            "SELECT COUNT(*) AS c FROM pending_transactions").fetchone()["c"]

    # The pending_transactions table doubles as the mempool subsystem's
    # write-behind journal (upow_tpu/mempool/): the in-memory pool is
    # the read authority, this table provides restart recovery and the
    # wallet CLI's direct-insert interop.  The stamp below is how the
    # pool detects journal movement it did not make itself — same
    # (count, max rowid, local generation) triple _pending_decoded uses.

    async def pending_journal_stamp(self) -> tuple:
        """Cheap change detector for the mempool journal."""
        r = self.db.execute(
            "SELECT COUNT(*) AS c, COALESCE(MAX(rowid), 0) AS m"
            " FROM pending_transactions").fetchone()
        return (r["c"], r["m"], self._pending_gen)

    async def load_pending_journal(self) -> List[dict]:
        """Every journal row the pool needs to rebuild itself
        (recovery load at startup, stamp-triggered reconcile after)."""
        rows = self.db.execute(
            "SELECT tx_hash, tx_hex, fees FROM pending_transactions"
        ).fetchall()
        return [{"tx_hash": r["tx_hash"], "tx_hex": r["tx_hex"],
                 "fees": r["fees"]} for r in rows]

    async def get_need_propagate_transactions(self, older_than: int = 300) -> List[str]:
        """Piggyback re-propagation queue (reference database.py:188-207)."""
        rows = self.db.execute(
            "SELECT tx_hex FROM pending_transactions WHERE propagation_time < ?",
            (now_ts() - older_than,),
        ).fetchall()
        return [r["tx_hex"] for r in rows]

    async def update_pending_transaction_propagation(self, tx_hash: str) -> None:
        self.db.execute(
            "UPDATE pending_transactions SET propagation_time = ? WHERE tx_hash = ?",
            (now_ts(), tx_hash),
        )
        self._commit()

    # --------------------------------------------------------------- UTXO --

    async def add_transaction_outputs(self, txs: Sequence[AnyTx]) -> None:
        """Route every output into its UTXO-class table
        (reference database.py:524-580).  Grouped into one executemany
        per table: an 8k-tx block is a handful of statement dispatches,
        not one per output."""
        by_table: Dict[str, list] = {}
        for tx in txs:
            h = tx.hash()
            for index, out in enumerate(tx.outputs):
                table = _OUTPUT_TABLE[out.output_type]
                by_table.setdefault(table, []).append((h, index, out))
        for table, entries in by_table.items():
            if table == "unspent_outputs":
                self.db.executemany(
                    "INSERT OR REPLACE INTO unspent_outputs (tx_hash, idx,"
                    " address, amount, is_stake) VALUES (?,?,?,?,?)",
                    [(h, i, o.address, o.amount, int(o.is_stake))
                     for h, i, o in entries],
                )
            else:
                self.db.executemany(
                    f"INSERT OR REPLACE INTO {table} (tx_hash, idx, address,"
                    " amount) VALUES (?,?,?,?)",
                    [(h, i, o.address, o.amount) for h, i, o in entries],
                )
            self._index_add(table, [(h, i) for h, i, _ in entries],
                            values=[(o.amount, o.address or "", 0)
                                    for _h, _i, o in entries])

    async def remove_outputs(self, txs: Sequence[AnyTx]) -> None:
        """Spend inputs from the table their tx type targets
        (reference database.py:589-622).  Grouped per table so a whole
        block is one DELETE executemany + one batched index apply per
        UTXO class, not one per tx."""
        by_table: Dict[str, list] = {}
        for tx in txs:
            if tx.is_coinbase:
                continue
            table = _INPUT_TABLE.get(tx.transaction_type, "unspent_outputs")
            by_table.setdefault(table, []).extend(
                (i.tx_hash, i.index) for i in tx.inputs)
        for table, outpoints in by_table.items():
            self.db.executemany(
                f"DELETE FROM {table} WHERE tx_hash = ? AND idx = ?",
                outpoints,
            )
            self._index_remove(table, outpoints)

    async def get_unspent_outpoints(self, table: str = "unspent_outputs") -> set:
        rows = self.db.execute(f"SELECT tx_hash, idx FROM {table}").fetchall()
        return {(r["tx_hash"], r["idx"]) for r in rows}

    async def outpoints_exist(self, outpoints: List[Tuple[str, int]],
                              table: str = "unspent_outputs") -> List[bool]:
        """Batched membership test: one row-value IN query per 400 outpoints
        instead of a query per outpoint — an 8k-input block is ~20 queries.
        (The reference does a set-diff against a full-column fetch,
        manager.py:531-615.)  With the device index enabled, the answer
        is EXACT and SQL-free: one ``searchsorted`` dispatch rejects
        definite misses, and the index's host-side exact map confirms
        the hits — including resolving 64-bit fingerprint twins down to
        the precise outpoint (see device_index.py).  The index is
        maintained in lockstep with every INSERT/DELETE on these tables
        and rebuilt on rollback, so its view always matches what this
        connection's SQL would report."""
        if not outpoints:
            return []
        if self._dev_index is not None and table in self._dev_index:
            present = self._dev_index[table].contains_batch(
                [tuple(o) for o in outpoints])
            return [bool(p) for p in present]
        return await self._outpoints_exist_sql(outpoints, table)

    async def _outpoints_exist_sql(self, outpoints: List[Tuple[str, int]],
                                   table: str) -> List[bool]:
        if not outpoints:
            return []
        found: set = set()
        CHUNK = 400
        for off in range(0, len(outpoints), CHUNK):
            chunk = outpoints[off:off + CHUNK]
            placeholders = ",".join(["(?,?)"] * len(chunk))
            params = [v for o in chunk for v in o]
            rows = self.db.execute(
                f"SELECT tx_hash, idx FROM {table} WHERE (tx_hash, idx)"
                f" IN (VALUES {placeholders})", params,
            ).fetchall()
            found.update((r["tx_hash"], r["idx"]) for r in rows)
        return [tuple(o) in found for o in outpoints]

    async def get_table_outpoints_hash(self, table: str) -> str:
        import hashlib

        rows = self.db.execute(
            f"SELECT tx_hash, idx FROM {table} ORDER BY tx_hash, idx"
        ).fetchall()
        h = hashlib.sha256()
        for r in rows:
            h.update(f"{r['tx_hash']}{r['idx']}".encode())
        return h.hexdigest()

    # ------------------------------------------------------ address views --

    async def _pending_filter(self, rows, check_pending_txs: bool) -> set:
        """Pending-spent overlay narrowed to these rows' outpoints (the
        full-overlay scan per lookup was quadratic under mempool load)."""
        if not check_pending_txs:
            return set()
        # threshold: narrowing wins when the row set is small (intake,
        # per-address lookups); full-table views (registrations,
        # ballots) would ship one bind param per row and invert the
        # cost model — there the one O(overlay) fetch stays cheaper,
        # and the cap also bounds the IN-clause parameter count
        if not rows:
            return set()
        if len(rows) > 256:
            return await self.get_pending_spent_outpoints()
        return await self.get_pending_spent_outpoints(
            [(r["tx_hash"], r["idx"]) for r in rows])

    async def get_spendable_outputs(self, address: str,
                                    check_pending_txs: bool = False) -> List[TxInput]:
        """REGULAR/UN_STAKE outputs owned by the address, minus anything in
        the pending-spent overlay when requested."""
        rows = self.db.execute(
            "SELECT tx_hash, idx, amount, is_stake FROM unspent_outputs"
            " WHERE address = ? AND is_stake = 0", (address,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            i = TxInput(r["tx_hash"], r["idx"])
            i.amount = r["amount"]
            out.append(i)
        return out

    async def get_stake_outputs(self, address: str,
                                check_pending_txs: bool = False) -> List[TxInput]:
        rows = self.db.execute(
            "SELECT tx_hash, idx, amount FROM unspent_outputs"
            " WHERE address = ? AND is_stake = 1", (address,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            i = TxInput(r["tx_hash"], r["idx"])
            i.amount = r["amount"]
            out.append(i)
        return out

    async def get_address_transactions(self, address: str, limit: int = 50,
                                       offset: int = 0) -> List[dict]:
        if self.archive is None:
            rows = self.db.execute(
                "SELECT t.*, b.id AS block_id, b.timestamp AS block_ts FROM transactions t"
                " JOIN blocks b ON b.hash = t.block_hash"
                " WHERE t.inputs_addresses LIKE ? OR t.outputs_addresses LIKE ?"
                " ORDER BY b.id DESC LIMIT ? OFFSET ?",
                (f'%"{address}"%', f'%"{address}"%', limit, offset),
            ).fetchall()
            return [dict(r) for r in rows]
        # archived history has to be merged in before paginating: fetch
        # the hot prefix deep enough to cover the requested page, then
        # overlay archive matches (dedup by tx_hash — witness txs below
        # the archive horizon exist in both tiers) and re-slice.  Any
        # hot row beyond the prefix sorts after >= offset+limit rows,
        # so it can never land inside the page.
        rows = self.db.execute(
            "SELECT t.*, b.id AS block_id, b.timestamp AS block_ts FROM transactions t"
            " JOIN blocks b ON b.hash = t.block_hash"
            " WHERE t.inputs_addresses LIKE ? OR t.outputs_addresses LIKE ?"
            " ORDER BY b.id DESC LIMIT ?",
            (f'%"{address}"%', f'%"{address}"%', offset + limit),
        ).fetchall()
        merged = [dict(r) for r in rows]
        seen = {r["tx_hash"] for r in merged}
        for b, t in await self.archive.address_history(address):
            if t[1] in seen:
                continue
            merged.append({
                "block_hash": t[0], "tx_hash": t[1], "tx_hex": t[2],
                "inputs_addresses": json.dumps(t[3]),
                "outputs_addresses": json.dumps(t[4]),
                "outputs_amounts": json.dumps(t[5]), "fees": t[6],
                "block_id": b[0], "block_ts": b[7],
            })
        merged.sort(key=lambda r: -r["block_id"])
        return merged[offset:offset + limit]

    # --------------------------------------------------------- governance --

    async def get_registered(self, table: str,
                             check_pending_txs: bool = False,
                             pending: Optional[set] = None) -> List[Tuple[str, int]]:
        """(address, registered_at block timestamp) per registration output."""
        rows = self.db.execute(
            f"SELECT g.tx_hash, g.idx, g.address FROM {table} g").fetchall()
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            ts = self.db.execute(
                "SELECT b.timestamp AS ts FROM transactions t JOIN blocks b"
                " ON b.hash = t.block_hash WHERE t.tx_hash = ?",
                (r["tx_hash"],),
            ).fetchone()
            out.append((r["address"], ts["ts"] if ts else now_ts()))
        return out

    async def get_ballot_by_recipient(self, table: str, recipient: str,
                                      check_pending_txs: bool = False) -> List[dict]:
        """Standing votes FOR ``recipient``.

        A ballot row is a vote *output*: its address column holds the vote
        RECIPIENT (the inode/validator being voted for); the VOTER is the
        vote transaction's ``inputs_addresses[output_index]`` (reference
        database.py:939-1063 — SQL 1-based ``inputs_addresses[index+1]``),
        and the vote count is the output's amount.
        """
        rows = self.db.execute(
            f"SELECT g.tx_hash, g.idx, g.amount FROM {table} g WHERE g.address = ?",
            (recipient,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            info = await self.get_transaction_info(r["tx_hash"])
            voter = None
            if info is not None and r["idx"] < len(info["inputs_addresses"]):
                voter = info["inputs_addresses"][r["idx"]]
            out.append({
                "tx_hash": r["tx_hash"], "index": r["idx"],
                "voter": voter, "vote": Decimal(r["amount"]) / SMALLEST,
            })
        return out

    async def _all_ballot_rows(self, table: str,
                               check_pending_txs: bool = False,
                               pending: Optional[set] = None) -> List[dict]:
        """Every standing ballot row with its voter resolved — ONE join
        instead of a query per recipient per row.  The voter rule (vote
        output's ``inputs_addresses[output_index]``) lives HERE only;
        get_votes_by_voter and get_active_inodes are filters over it."""
        rows = self.db.execute(
            f"SELECT g.tx_hash, g.idx, g.address AS recipient, g.amount,"
            f" t.inputs_addresses FROM {table} g"
            f" JOIN transactions t ON t.tx_hash = g.tx_hash"
        ).fetchall()
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        out = []
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            addrs = json.loads(r["inputs_addresses"])
            voter = addrs[r["idx"]] if r["idx"] < len(addrs) else None
            out.append({
                "tx_hash": r["tx_hash"], "index": r["idx"],
                "recipient": r["recipient"], "voter": voter,
                "vote": Decimal(r["amount"]) / SMALLEST,
            })
        return out

    async def get_transaction_block_timestamp(self, tx_hash: str) -> Optional[int]:
        r = self.db.execute(
            "SELECT b.timestamp AS ts FROM transactions t JOIN blocks b ON"
            " b.hash = t.block_hash WHERE t.tx_hash = ?", (tx_hash,),
        ).fetchone()
        if r is None and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                b = await self.archive.block_by_height(hit[1])
                return b[7] if b else None
        return r["ts"] if r else None

    async def get_delegates_voting_power(self, address: str,
                                         check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        rows = self.db.execute(
            "SELECT tx_hash, idx FROM delegates_voting_power WHERE address = ?",
            (address,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        return [(r["tx_hash"], r["idx"]) for r in rows
                if (r["tx_hash"], r["idx"]) not in pending]

    async def get_inode_registration_outputs(self, address: str,
                                             check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        rows = self.db.execute(
            "SELECT tx_hash, idx FROM inode_registration_output WHERE address = ?",
            (address,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        return [(r["tx_hash"], r["idx"]) for r in rows
                if (r["tx_hash"], r["idx"]) not in pending]

    async def get_validators_voting_power(self, address: str,
                                          check_pending_txs: bool = False) -> List[Tuple[str, int]]:
        """Unspent VALIDATOR_VOTING_POWER outputs owned by the address."""
        rows = self.db.execute(
            "SELECT tx_hash, idx FROM validators_voting_power WHERE address = ?",
            (address,),
        ).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        return [(r["tx_hash"], r["idx"]) for r in rows
                if (r["tx_hash"], r["idx"]) not in pending]

    async def get_multiple_address_stakes(
            self, addresses: Iterable[str],
            check_pending_txs: bool = False,
            pending: Optional[set] = None) -> Dict[str, Decimal]:
        """Batch stake query (reference database.py:1208-1290): one pass over
        unspent stake outputs + one pass over the mempool for all addresses."""
        addresses = list(set(addresses))
        if not addresses:
            return {}
        out: Dict[str, Decimal] = {a: Decimal(0) for a in addresses}
        placeholders = ",".join("?" * len(addresses))
        rows = self.db.execute(
            f"SELECT tx_hash, idx, address, amount FROM unspent_outputs"
            f" WHERE is_stake = 1 AND address IN ({placeholders})", addresses,
        ).fetchall()
        if pending is None:
            pending = await self._pending_filter(rows, check_pending_txs)
        for r in rows:
            if (r["tx_hash"], r["idx"]) in pending:
                continue
            out[r["address"]] += Decimal(r["amount"]) / SMALLEST
        if check_pending_txs:
            want = set(addresses)
            for tx in (await self._pending_decoded()).values():
                for o in tx.outputs:
                    if o.is_stake and o.address in want:
                        out[o.address] += Decimal(o.amount) / SMALLEST
        return out

    async def get_outputs_by_address(self, table: str, address: str,
                                     check_pending_txs: bool = False,
                                     is_stake: Optional[bool] = None) -> List[dict]:
        """Generic per-table output listing: {tx_hash, index, amount} rows
        (the shape the address-info endpoint sections need)."""
        sql = f"SELECT tx_hash, idx, amount FROM {table} WHERE address = ?"
        params: list = [address]
        if is_stake is not None and table == "unspent_outputs":
            sql += " AND is_stake = ?"
            params.append(int(is_stake))
        rows = self.db.execute(sql, params).fetchall()
        pending = await self._pending_filter(rows, check_pending_txs)
        return [
            {"tx_hash": r["tx_hash"], "index": r["idx"], "amount": r["amount"]}
            for r in rows if (r["tx_hash"], r["idx"]) not in pending
        ]

    # ------------------------------------------------------ explorer views --

    async def get_ballots(self, table: str, recipient: Optional[str] = None,
                          offset: int = 0, limit: int = 100) -> List[dict]:
        """Paged ballot listing for the validators/delegates info endpoints
        (reference database.py get_inode_ballot/get_validator_ballot):
        rows of {tx_hash, index, voter, recipient, vote}."""
        if recipient is not None:
            rows = self.db.execute(
                f"SELECT tx_hash, idx, address, amount FROM {table}"
                f" WHERE address = ? LIMIT ? OFFSET ?",
                (recipient, limit, offset),
            ).fetchall()
        else:
            rows = self.db.execute(
                f"SELECT tx_hash, idx, address, amount FROM {table}"
                f" LIMIT ? OFFSET ?", (limit, offset),
            ).fetchall()
        out = []
        for r in rows:
            info = await self.get_transaction_info(r["tx_hash"])
            voter = None
            if info is not None and r["idx"] < len(info["inputs_addresses"]):
                voter = info["inputs_addresses"][r["idx"]]
            out.append({
                "tx_hash": r["tx_hash"], "index": r["idx"], "voter": voter,
                "recipient": r["address"],
                "vote": Decimal(r["amount"]) / SMALLEST,
            })
        return out

    async def get_nice_transaction(self, tx_hash: str,
                                   address: Optional[str] = None) -> Optional[dict]:
        """Explorer-style decoded transaction (reference database.py:1606-1654).
        Amounts are coin-denominated floats like the reference's JSON."""
        r = self.db.execute(
            "SELECT t.*, b.id AS block_no, b.timestamp AS block_ts FROM"
            " transactions t JOIN blocks b ON b.hash = t.block_hash"
            " WHERE t.tx_hash = ?", (tx_hash,),
        ).fetchone()
        is_confirm = r is not None
        if r is None:
            r = self.db.execute(
                "SELECT tx_hash, tx_hex, inputs_addresses FROM"
                " pending_transactions WHERE tx_hash = ?", (tx_hash,),
            ).fetchone()
        if r is None and self.archive is not None:
            hit = await self.archive.tx_by_hash(tx_hash)
            if hit is not None:
                t, height = hit
                b = await self.archive.block_by_height(height)
                # plain dict stands in for the sqlite Row (same keys,
                # .keys() works; inputs_addresses json-encoded like the
                # hot column)
                r = {"tx_hash": t[1], "tx_hex": t[2],
                     "inputs_addresses": json.dumps(t[3]),
                     "block_hash": t[0], "block_no": height,
                     "block_ts": b[7] if b else None}
                is_confirm = True
        if r is None:
            return None
        keys = r.keys()
        tx = tx_from_hex(r["tx_hex"], check_signatures=False)
        inputs_addresses = json.loads(r["inputs_addresses"])

        def coins(amount: int) -> float:
            return float(Decimal(amount) / SMALLEST)

        if tx.is_coinbase:
            out = {
                "is_coinbase": True, "hash": r["tx_hash"],
                "block_hash": r["block_hash"] if "block_hash" in keys else None,
                "block_no": r["block_no"] if "block_no" in keys else None,
                "datetime": r["block_ts"] if "block_ts" in keys else None,
            }
        else:
            delta = None
            if address is not None:
                delta = 0
                for i, tx_input in enumerate(tx.inputs):
                    if i < len(inputs_addresses) and inputs_addresses[i] == address:
                        amt = await self.get_output_amount(
                            tx_input.tx_hash, tx_input.index)
                        delta -= amt or 0
                for o in tx.outputs:
                    if o.address == address:
                        delta += o.amount
                delta = coins(delta)
            inputs = []
            for i, tx_input in enumerate(tx.inputs):
                amt = await self.get_output_amount(tx_input.tx_hash, tx_input.index)
                inputs.append({
                    "index": tx_input.index,
                    "tx_hash": tx_input.tx_hash,
                    "address": (inputs_addresses[i]
                                if i < len(inputs_addresses) else None),
                    "amount": coins(amt or 0),
                })
            out = {
                "is_coinbase": False, "hash": r["tx_hash"],
                "block_hash": r["block_hash"] if "block_hash" in keys else None,
                "block_no": r["block_no"] if "block_no" in keys else None,
                "datetime": r["block_ts"] if "block_ts" in keys else None,
                "message": tx.message.hex() if tx.message is not None else None,
                "transaction_type": tx.transaction_type.name,
                "is_confirm": is_confirm,
                "inputs": inputs,
                "delta": delta,
                "fees": coins(await self.tx_fees(tx)),
            }
        out["outputs"] = [
            {"address": o.address, "amount": coins(o.amount),
             "type": o.output_type.name}
            for o in tx.outputs
        ]
        return out

    async def get_block_transaction_hashes(self, block_hash: str) -> List[str]:
        rows = self.db.execute(
            "SELECT tx_hash FROM transactions WHERE block_hash = ?",
            (block_hash,),
        ).fetchall()
        if not rows and self.archive is not None:
            atxs = await self.archive.txs_for_block(block_hash)
            if atxs:
                return [t[1] for t in atxs]
        return [r["tx_hash"] for r in rows]

    async def get_address_pending_transactions(self, address: str) -> List[Tx]:
        """Mempool txs touching the address (input spender or output
        recipient)."""
        rows = self.db.execute(
            "SELECT tx_hex, inputs_addresses FROM pending_transactions"
        ).fetchall()
        out = []
        for r in rows:
            if address in json.loads(r["inputs_addresses"]):
                out.append(tx_from_hex(r["tx_hex"], check_signatures=False))
                continue
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            if any(o.address == address for o in tx.outputs):
                out.append(tx)
        return out

    async def get_address_pending_spent_outpoints(
            self, address: str) -> List[Tuple[str, int]]:
        """Outpoints of this address currently referenced by mempool txs."""
        rows = self.db.execute(
            "SELECT tx_hex, inputs_addresses FROM pending_transactions"
        ).fetchall()
        out = []
        for r in rows:
            addrs = json.loads(r["inputs_addresses"])
            tx = tx_from_hex(r["tx_hex"], check_signatures=False)
            for i, tx_input in enumerate(tx.inputs):
                if i < len(addrs) and addrs[i] == address:
                    out.append((tx_input.tx_hash, tx_input.index))
        return out

    # ----------------------------------------------------------- rebuild --

    async def rebuild_utxos(self) -> None:
        """Full-chain replay of every output table from the transactions log
        (reference create_unspent_outputs.py + database.py:846-862) — the
        consensus-bug detector: any divergence from live tables is a bug."""
        for table in ("unspent_outputs",) + _GOV_TABLES:
            self.db.execute(f"DELETE FROM {table}")
        rows = self.db.execute(
            "SELECT t.tx_hex FROM transactions t JOIN blocks b ON"
            " b.hash = t.block_hash ORDER BY b.id"
        ).fetchall()
        txs = [tx_from_hex(r["tx_hex"], check_signatures=False) for r in rows]
        for tx in txs:
            await self.add_transaction_outputs([tx])
            await self.remove_outputs([tx])
        self._commit()
        self._index_rebuild()  # replay rewrote the tables wholesale

    # ---------------------------------------------------------- snapshots --
    # Canonical positional row shapes shared with the pg backend (the
    # snapshot payload is backend-neutral, docs/SNAPSHOT.md):
    #   unspent_outputs  [tx_hash, idx, address|null, amount, is_stake]
    #   governance       [tx_hash, idx, address|null, amount]
    #   tx               [block_hash, tx_hash, tx_hex, inputs_addresses,
    #                     outputs_addresses, outputs_amounts, fees]
    #   block            [id, hash, content, address, random,
    #                     str(difficulty), reward, timestamp]
    # Amounts/fees/rewards are int smallest-units everywhere; lists are
    # real JSON arrays (this backend stores them json-encoded).

    async def export_snapshot_rows(self, table: str) -> List[list]:
        if table not in ("unspent_outputs",) + _GOV_TABLES:
            raise ValueError(f"not a snapshot table: {table}")
        if table == "unspent_outputs":
            rows = self.db.execute(
                "SELECT tx_hash, idx, address, amount, is_stake FROM"
                " unspent_outputs ORDER BY tx_hash, idx").fetchall()
            return [[r["tx_hash"], r["idx"], r["address"], r["amount"],
                     r["is_stake"]] for r in rows]
        rows = self.db.execute(
            f"SELECT tx_hash, idx, address, amount FROM {table}"
            " ORDER BY tx_hash, idx").fetchall()
        return [[r["tx_hash"], r["idx"], r["address"], r["amount"]]
                for r in rows]

    async def export_snapshot_txs(self, tail: int) -> List[list]:
        """Witness transactions: every tx still referenced by an
        exported outpoint (the pg schema resolves amounts through — and
        foreign-keys onto — the transactions table, so UTXO rows alone
        cannot restore there) plus all txs of the carried block tail."""
        union = " UNION ".join(
            f"SELECT tx_hash FROM {t}"
            for t in ("unspent_outputs",) + _GOV_TABLES)
        rows = self.db.execute(
            "SELECT block_hash, tx_hash, tx_hex, inputs_addresses,"
            " outputs_addresses, outputs_amounts, fees FROM transactions"
            f" WHERE tx_hash IN ({union}) OR block_hash IN"
            " (SELECT hash FROM blocks ORDER BY id DESC LIMIT ?)"
            " ORDER BY tx_hash", (tail,)).fetchall()
        return [[r["block_hash"], r["tx_hash"], r["tx_hex"],
                 json.loads(r["inputs_addresses"]),
                 json.loads(r["outputs_addresses"]),
                 json.loads(r["outputs_amounts"]), r["fees"]] for r in rows]

    async def export_snapshot_blocks(self, tail: int) -> List[list]:
        rows = self.db.execute(
            "SELECT id, hash, content, address, random, difficulty,"
            " reward, timestamp FROM blocks ORDER BY id DESC LIMIT ?",
            (tail,)).fetchall()
        return [[r["id"], r["hash"], r["content"], r["address"],
                 r["random"], str(r["difficulty"]), r["reward"],
                 r["timestamp"]] for r in reversed(rows)]

    async def restore_snapshot(self, tables: Dict[str, List[list]],
                               txs: List[list], blocks: List[list]) -> None:
        """Wholesale replace of chain state with verified snapshot rows.
        Callers verify every chunk hash AND the recomputed UTXO
        fingerprint against the manifest BEFORE calling — one
        transaction, so a crash mid-restore leaves the previous state
        intact (atomic() rolls back)."""
        for name in tables:
            if name not in ("unspent_outputs",) + _GOV_TABLES:
                raise ValueError(f"not a snapshot table: {name}")
        async with self.atomic():
            for table in ("unspent_outputs",) + _GOV_TABLES:
                self.db.execute(f"DELETE FROM {table}")
            for table in ("pending_spent_outputs", "pending_transactions",
                          "transactions", "blocks"):
                self.db.execute(f"DELETE FROM {table}")
            self.db.executemany(
                "INSERT INTO blocks (id, hash, content, address, random,"
                " difficulty, reward, timestamp) VALUES (?,?,?,?,?,?,?,?)",
                [tuple(r) for r in blocks])
            self.db.executemany(
                "INSERT INTO transactions (block_hash, tx_hash, tx_hex,"
                " inputs_addresses, outputs_addresses, outputs_amounts,"
                " fees) VALUES (?,?,?,?,?,?,?)",
                [(r[0], r[1], r[2], json.dumps(r[3]), json.dumps(r[4]),
                  json.dumps(r[5]), r[6]) for r in txs])
            self.db.executemany(
                "INSERT INTO unspent_outputs (tx_hash, idx, address,"
                " amount, is_stake) VALUES (?,?,?,?,?)",
                [tuple(r) for r in tables.get("unspent_outputs", [])])
            for table in _GOV_TABLES:
                self.db.executemany(
                    f"INSERT INTO {table} (tx_hash, idx, address, amount)"
                    " VALUES (?,?,?,?)",
                    [tuple(r) for r in tables.get(table, [])])
        self._amount_cache.clear()
        self._bump_fees_gen()
        self._index_rebuild()  # restore rewrote the tables wholesale

    # ------------------------------------------------------------- archive --
    # Compactor seam (upow_tpu/archive/compactor.py, docs/ARCHIVE.md).
    # Export reuses the canonical positional row shapes above; prune
    # evaluates the witness closure live, at delete time, so re-running
    # after a crash is an idempotent no-op for already-pruned rows.

    async def archive_export_span(self, lo: int, hi: int):
        """Canonical rows for heights [lo, hi]: (block rows ascending,
        {block_hash: [tx rows in acceptance order]})."""
        rows = self.db.execute(
            "SELECT id, hash, content, address, random, difficulty,"
            " reward, timestamp FROM blocks WHERE id BETWEEN ? AND ?"
            " ORDER BY id", (lo, hi)).fetchall()
        blocks = [[r["id"], r["hash"], r["content"], r["address"],
                   r["random"], str(r["difficulty"]), r["reward"],
                   r["timestamp"]] for r in rows]
        txs_by_block: Dict[str, list] = {}
        hashes = [b[1] for b in blocks]
        for i in range(0, len(hashes), 900):
            chunk = hashes[i:i + 900]
            marks = ",".join("?" * len(chunk))
            for t in self.db.execute(
                    "SELECT block_hash, tx_hash, tx_hex,"
                    " inputs_addresses, outputs_addresses,"
                    " outputs_amounts, fees FROM transactions WHERE"
                    f" block_hash IN ({marks}) ORDER BY rowid", chunk):
                txs_by_block.setdefault(t["block_hash"], []).append(
                    [t["block_hash"], t["tx_hash"], t["tx_hex"],
                     json.loads(t["inputs_addresses"]),
                     json.loads(t["outputs_addresses"]),
                     json.loads(t["outputs_amounts"]), t["fees"]])
        return blocks, txs_by_block

    async def archive_prune_span(self, lo: int, hi: int) -> dict:
        """Delete hot blocks in [lo, hi] whose ENTIRE tx set is outside
        the snapshot witness closure, plus those blocks' txs.  A block
        with even one witness tx keeps ALL its rows hot, so a block's
        txs are never split across the hot/archive seam and every hot
        join stays intact."""
        union = " UNION ".join(
            f"SELECT tx_hash FROM {t}"
            for t in ("unspent_outputs",) + _GOV_TABLES)
        doomed = [r["hash"] for r in self.db.execute(
            "SELECT hash FROM blocks b WHERE b.id BETWEEN ? AND ?"
            " AND NOT EXISTS (SELECT 1 FROM transactions t WHERE"
            f" t.block_hash = b.hash AND t.tx_hash IN ({union}))",
            (lo, hi)).fetchall()]
        tx_hashes: List[str] = []
        for i in range(0, len(doomed), 900):
            chunk = doomed[i:i + 900]
            marks = ",".join("?" * len(chunk))
            tx_hashes.extend(r["tx_hash"] for r in self.db.execute(
                "SELECT tx_hash FROM transactions WHERE block_hash IN"
                f" ({marks})", chunk))
            self.db.execute(
                f"DELETE FROM transactions WHERE block_hash IN ({marks})",
                chunk)
            self.db.execute(
                f"DELETE FROM blocks WHERE hash IN ({marks})", chunk)
        self._amount_cache_drop(tx_hashes)
        self._commit()
        return {"blocks": len(doomed), "txs": len(tx_hashes)}

    async def archive_hot_row_counts(self) -> dict:
        b = self.db.execute(
            "SELECT COUNT(*) AS n FROM blocks").fetchone()["n"]
        t = self.db.execute(
            "SELECT COUNT(*) AS n FROM transactions").fetchone()["n"]
        return {"blocks": b, "txs": t}
