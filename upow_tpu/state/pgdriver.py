"""Drivers for the PostgreSQL chain-state backend (state/pg.py).

Two implementations of one small synchronous facade:

* :class:`AsyncpgDriver` — production: asyncpg (the reference's own
  driver, database.py:33-91) behind a dedicated event-loop thread, so
  the storage layer keeps the same short-synchronous-call model the
  sqlite backend uses.  asyncpg is imported lazily: it is not part of
  this framework's baseline dependencies and only needed when an
  operator points the node at a PostgreSQL uPow database.

* :class:`MockPgDriver` — tests: executes the same pg-dialect SQL
  against stdlib sqlite, translating ``$n`` placeholders and the
  handful of type-representation differences (TEXT[]/BIGINT[] arrays,
  NUMERIC, TIMESTAMP, BOOLEAN).  This is what lets the PgChainState SQL
  and conversion logic run under CI with no server; the identical suite
  runs against a real server when ``UPOW_PG_DSN`` is set.

The SQL subset the pg backend restricts itself to (so both drivers
behave identically): explicit column lists, ``$n`` parameters, whole
arrays as values (never indexed/ANY'd in SQL — the one exception,
``= ANY(col)``, is translated by the mock), no NOW() (timestamps are
passed in), row-value IN lists built with explicit placeholders.
"""

from __future__ import annotations

import asyncio
import datetime
import decimal as decimal_mod
import json
import re
import threading
from decimal import Decimal
from functools import lru_cache
from typing import Any, Iterable, List, Optional, Sequence


class PgDriverError(Exception):
    """Driver-neutral error taxonomy.  AsyncpgDriver maps asyncpg's
    SQLSTATE-classed exceptions onto these; MockPgDriver maps sqlite's —
    so storage-layer code (and tests) can catch ONE set of classes with
    both drivers.  ``sqlstate`` carries the PostgreSQL class code."""

    sqlstate: Optional[str] = None


class IntegrityViolation(PgDriverError):
    sqlstate = "23000"


class UniqueViolation(IntegrityViolation):
    sqlstate = "23505"


class ForeignKeyViolation(IntegrityViolation):
    sqlstate = "23503"


class NumericValueOutOfRange(PgDriverError):
    sqlstate = "22003"


def _map_asyncpg_error(e):
    """asyncpg.PostgresError -> the shim taxonomy (by SQLSTATE)."""
    code = getattr(e, "sqlstate", None) or ""
    if code == "23505":
        cls = UniqueViolation
    elif code == "23503":
        cls = ForeignKeyViolation
    elif code.startswith("23"):
        cls = IntegrityViolation
    elif code == "22003":
        cls = NumericValueOutOfRange
    else:
        return e  # pass through: connection/protocol errors keep their type
    out = cls(str(e))
    out.sqlstate = code
    return out


def _map_sqlite_error(e):
    """sqlite3.IntegrityError -> the shim taxonomy (by message)."""
    msg = str(e)
    if "UNIQUE constraint" in msg:
        return UniqueViolation(msg)
    if "FOREIGN KEY constraint" in msg:
        return ForeignKeyViolation(msg)
    return IntegrityViolation(msg)


def _utc(dt_or_epoch) -> datetime.datetime:
    """Naive-UTC datetime (what the reference stores in TIMESTAMP(0)
    columns via datetime.utcfromtimestamp)."""
    if isinstance(dt_or_epoch, datetime.datetime):
        return dt_or_epoch
    return datetime.datetime.fromtimestamp(
        int(dt_or_epoch), datetime.timezone.utc).replace(tzinfo=None)


def _epoch(dt) -> int:
    if isinstance(dt, (int, float)):
        return int(dt)
    return int(dt.replace(tzinfo=datetime.timezone.utc).timestamp())


class AsyncpgDriver:
    """One asyncpg connection on a private loop thread, sync facade.

    Single-connection by design: block acceptance wraps BEGIN/COMMIT
    around the connection (a pool would break that transaction
    affinity).  asyncpg allows ONE operation in flight per connection,
    so every facade call — sync or awaitable — runs under a per-
    statement lock on the driver loop; transaction-scope exclusivity
    (no foreign writer joining an open BEGIN) is the storage layer's
    job (PgChainState's writer lock).

    Two call styles:

    * ``afetch``/``aexecute``/... — awaitable from the node's event
      loop: the coroutine runs on the driver thread's loop and the
      caller awaits a wrapped future, so a network round trip never
      blocks the node (gossip, heartbeats and other endpoints keep
      being served during storage I/O).  This is what PgChainState's
      async methods use.
    * ``fetch``/``execute``/... — synchronous, blocking the calling
      thread for one round trip; for CLI tools (reindex) and tests.

    The storage layer additionally batches its hot paths into
    executemany/JOIN shapes to keep statements-per-block low;
    deployments should still colocate the node with the database (the
    reference's asyncpg setup assumes the same).
    """

    def __init__(self, dsn: str):
        self._dsn = dsn
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="pg-driver")
        self._thread.start()
        # per-statement serialization: asyncpg raises InterfaceError on
        # a second in-flight operation; the lock lives on (binds to) the
        # driver loop where every operation runs
        self._oplock = None
        # transaction state, mutated ONLY on the driver loop inside the
        # op lock (see the _do_* helpers): _txn_open tracks an open
        # BEGIN; _txn_lost poisons writes after a mid-transaction
        # connection loss until the owner rolls back.
        self._txn_open = False
        self._txn_lost = False
        self._conn = self._call(self._connect(dsn))

    async def _connect(self, dsn: str):
        import asyncpg  # lazy: only a pg-backed node pays this import

        return await asyncpg.connect(dsn)

    async def _ensure_conn(self):
        """Reconnect once if the server dropped the connection (restart,
        idle timeout) — the reference's pool does the same implicitly
        (database.py:36-43).  Runs under the op lock, so no statement is
        in flight while the connection is swapped.

        A drop MID-TRANSACTION poisons writes (``_txn_lost``) rather
        than raising at whoever happens to touch the connection next:
        the server already rolled the transaction back, so the OWNER's
        next write/COMMIT must fail loudly (a COMMIT on the fresh
        connection would be a silent no-op), while incidental readers
        are fine on the fresh connection."""
        if self._conn.is_closed():
            import logging

            logging.getLogger("upow_tpu.state").warning(
                "pg connection lost; reconnecting")
            self._conn = await self._connect(self._dsn)
            if self._txn_open:
                self._txn_open = False
                self._txn_lost = True

    def _check_not_lost(self):
        if self._txn_lost:
            raise ConnectionError(
                "pg connection was lost mid-transaction; the open "
                "transaction was rolled back server-side — roll back "
                "and retry")

    # the _do_* helpers run on the driver loop under the op lock, so
    # transaction-state reads/writes are race-free

    async def _do_fetch(self, sql, args):
        return await self._conn.fetch(sql, *args)

    async def _do_execute(self, sql, args):
        self._check_not_lost()
        return await self._conn.execute(sql, *args)

    async def _do_executemany(self, sql, rows):
        self._check_not_lost()
        return await self._conn.executemany(sql, rows)

    async def _do_begin(self):
        self._check_not_lost()
        await self._conn.execute("BEGIN")
        self._txn_open = True

    async def _do_commit(self):
        self._check_not_lost()
        await self._conn.execute("COMMIT")
        self._txn_open = False

    async def _do_rollback(self):
        # clears the poison: nothing is left to roll back server-side
        # after a connection loss, and the caller has now observed it
        try:
            if not self._txn_lost:
                await self._conn.execute("ROLLBACK")
        finally:
            self._txn_open = False
            self._txn_lost = False

    def _call(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    def _submit(self, coro):
        """Awaitable-from-any-loop handle for a coroutine running on the
        driver thread's loop."""
        return asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(coro, self._loop))

    async def _locked(self, op):
        if self._oplock is None:
            self._oplock = asyncio.Lock()
        async with self._oplock:
            await self._ensure_conn()
            try:
                return await op()
            except Exception as e:
                import asyncpg

                if isinstance(e, asyncpg.PostgresError):
                    mapped = _map_asyncpg_error(e)
                    if mapped is not e:
                        raise mapped from e
                raise

    # -- sync facade (CLI tools, tests) --

    def fetch(self, sql: str, args: Sequence[Any] = ()) -> List[Any]:
        return self._call(self._locked(lambda: self._do_fetch(sql, args)))

    def execute(self, sql: str, args: Sequence[Any] = ()) -> None:
        self._call(self._locked(lambda: self._do_execute(sql, args)))

    def executemany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        rows = list(rows)
        if rows:
            self._call(self._locked(lambda: self._do_executemany(sql, rows)))

    def begin(self) -> None:
        self._call(self._locked(self._do_begin))

    def commit(self) -> None:
        self._call(self._locked(self._do_commit))

    def rollback(self) -> None:
        self._call(self._locked(self._do_rollback))

    # -- awaitable facade (the node's event loop) --

    async def afetch(self, sql: str, args: Sequence[Any] = ()) -> List[Any]:
        return await self._submit(
            self._locked(lambda: self._do_fetch(sql, args)))

    async def aexecute(self, sql: str, args: Sequence[Any] = ()) -> None:
        await self._submit(
            self._locked(lambda: self._do_execute(sql, args)))

    async def aexecutemany(self, sql: str,
                           rows: Iterable[Sequence[Any]]) -> None:
        rows = list(rows)
        if rows:
            await self._submit(
                self._locked(lambda: self._do_executemany(sql, rows)))

    async def abegin(self) -> None:
        await self._submit(self._locked(self._do_begin))

    async def acommit(self) -> None:
        await self._submit(self._locked(self._do_commit))

    async def arollback(self) -> None:
        await self._submit(self._locked(self._do_rollback))

    def close(self) -> None:
        try:
            self._call(self._conn.close())
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)


# --- mock driver ---------------------------------------------------------

# Output-column representation map (reference schema.sql types).  The pg
# backend's SQL keeps these column names stable (including aliases), so
# name-based conversion is unambiguous.
_ARRAY_COLS = {"inputs_addresses", "outputs_addresses", "outputs_amounts"}
_NUMERIC_COLS = {"fees", "reward", "difficulty"}
_TIMESTAMP_COLS = {"timestamp", "propagation_time", "block_ts", "ts"}
_BOOL_COLS = {"is_stake"}

# sqlite DDL mirroring schema.sql's tables (same names, sqlite types);
# "index" is kept verbatim — sqlite accepts it quoted.  journal_seq is
# the PG_SCHEMA migration column (pg: BIGINT DEFAULT nextval); INTEGER
# PRIMARY KEY AUTOINCREMENT reproduces the never-reissued monotonic
# assignment the mempool stamp relies on.
_MOCK_DDL = """
CREATE TABLE IF NOT EXISTS blocks (
    id INTEGER PRIMARY KEY,
    hash TEXT UNIQUE,
    content TEXT NOT NULL,
    address TEXT NOT NULL,
    random INTEGER NOT NULL,
    difficulty TEXT NOT NULL,
    reward TEXT NOT NULL,
    timestamp INTEGER
);
CREATE TABLE IF NOT EXISTS transactions (
    block_hash TEXT NOT NULL,
    tx_hash TEXT UNIQUE,
    tx_hex TEXT,
    inputs_addresses TEXT,
    outputs_addresses TEXT,
    outputs_amounts TEXT,
    fees TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS unspent_outputs (
    tx_hash TEXT,
    "index" INTEGER NOT NULL,
    address TEXT NULL,
    is_stake INTEGER
);
CREATE TABLE IF NOT EXISTS pending_transactions (
    journal_seq INTEGER PRIMARY KEY AUTOINCREMENT,
    tx_hash TEXT UNIQUE,
    tx_hex TEXT,
    inputs_addresses TEXT,
    fees TEXT NOT NULL,
    propagation_time INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS pending_spent_outputs (
    tx_hash TEXT,
    "index" INTEGER NOT NULL
);
"""
for _t in ("inode_registration_output", "validator_registration_output",
           "validators_voting_power", "delegates_voting_power",
           "validators_ballot", "inodes_ballot"):
    _MOCK_DDL += f"""
CREATE TABLE IF NOT EXISTS {_t} (
    tx_hash TEXT,
    "index" INTEGER NOT NULL,
    address TEXT NULL
);
"""

_PLACEHOLDER = re.compile(r"\$(\d+)")
_ANY_CLAUSE = re.compile(r"\$(\d+)\s*=\s*ANY\s*\(\s*(\w+)\s*\)")
_ANY_PARAM = re.compile(r"(\w+)\s*=\s*ANY\s*\(\s*\$(\d+)\s*\)")
_INSERT_COLS = re.compile(
    r"INSERT\s+INTO\s+\w+\s*\(([^)]*)\)\s*VALUES\s*\(([^)]*)\)", re.I)

# reference schema.sql column types the mock must emulate on WRITE:
# NUMERIC(p, s) quantizes (PostgreSQL rounds half away from zero) and
# raises numeric_value_out_of_range when integer digits exceed p - s;
# TIMESTAMP(0) rounds fractional seconds to the nearest second
_NUMERIC_SPEC = {"difficulty": (3, 1), "reward": (14, 6), "fees": (14, 6)}
_TS0_COLS = {"timestamp", "propagation_time"}


def _quantize_numeric(value: Decimal, col: str) -> Decimal:
    precision, scale = _NUMERIC_SPEC[col]
    q = value.quantize(Decimal(1).scaleb(-scale),
                       rounding=decimal_mod.ROUND_HALF_UP)
    if q.adjusted() + 1 > precision - scale:
        raise NumericValueOutOfRange(
            f"numeric field overflow: {col} NUMERIC({precision},{scale}) "
            f"cannot hold {value}")
    return q


class MockPgDriver:
    """sqlite stand-in executing the pg backend's SQL (tests only)."""

    supports_composite_types = False  # no CREATE TYPE in sqlite
    schema_preinstalled = True  # __init__ applies the sqlite-dialect DDL
    # (the reference-dialect PG_SCHEMA text is pg-only: it spells the
    # outpoint column as unquoted `index`, reserved in sqlite)

    def __init__(self, threadsafe: bool = False):
        import sqlite3

        # threadsafe=True lets the fake-asyncpg harness (tests/
        # fake_asyncpg.py) share this sqlite handle across the main
        # thread and AsyncpgDriver's loop thread; the driver's
        # per-statement lock serializes actual use.
        self.db = sqlite3.connect(":memory:",
                                  check_same_thread=not threadsafe)
        self.db.isolation_level = None  # autocommit; BEGIN/COMMIT explicit
        self.db.row_factory = sqlite3.Row
        self.db.executescript(_MOCK_DDL)

    # -- translation --

    @staticmethod
    def _convert_in(value):
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, list):
            return json.dumps(value)
        if isinstance(value, datetime.datetime):
            return _epoch(value)
        if isinstance(value, Decimal):
            return str(value)
        return value

    @staticmethod
    def _convert_out(row) -> dict:
        out = {}
        for key in row.keys():
            v = row[key]
            if v is None:
                out[key] = None
            elif key in _ARRAY_COLS:
                out[key] = json.loads(v)
            elif key in _NUMERIC_COLS:
                out[key] = Decimal(str(v))
            elif key in _TIMESTAMP_COLS:
                out[key] = _utc(v)
            elif key in _BOOL_COLS:
                out[key] = bool(v)
            else:
                out[key] = v
        return out

    @classmethod
    def _translate(cls, sql: str):
        """pg-dialect SQL -> (sqlite SQL using :pN named params)."""
        # `$k = ANY(col)`: pg array membership -> sqlite json_each scan
        sql = _ANY_CLAUSE.sub(
            r"EXISTS (SELECT 1 FROM json_each(\2) WHERE"
            r" json_each.value = :p\1)", sql)
        # `col = ANY($k)`: asyncpg list param -> IN over the JSON array
        # the list converts to
        sql = _ANY_PARAM.sub(
            r"\1 IN (SELECT value FROM json_each(:p\2))", sql)
        return _PLACEHOLDER.sub(r":p\1", sql)

    @staticmethod
    @lru_cache(maxsize=256)
    def _insert_param_cols(pg_sql: str) -> tuple:
        """For INSERT statements: map 1-based param index -> column name
        (None where the value isn't a bare placeholder), so write-side
        type semantics (NUMERIC quantization, TIMESTAMP(0) rounding)
        apply to the right params."""
        m = _INSERT_COLS.search(pg_sql)
        if not m:
            return ()
        cols = [c.strip().strip('"') for c in m.group(1).split(",")]
        out = {}
        for col, val in zip(cols, m.group(2).split(",")):
            pm = re.fullmatch(r"\s*\$(\d+)\s*", val)
            if pm:
                out[int(pm.group(1))] = col
        return tuple(sorted(out.items()))

    def _params(self, args: Sequence[Any], pg_sql: str = "") -> dict:
        by_idx = dict(self._insert_param_cols(pg_sql)) if pg_sql else {}
        out = {}
        for i, v in enumerate(args):
            col = by_idx.get(i + 1)
            if isinstance(v, Decimal) and col in _NUMERIC_SPEC:
                v = _quantize_numeric(v, col)
            elif isinstance(v, datetime.datetime) and col in _TS0_COLS \
                    and v.microsecond:
                v = v.replace(microsecond=0) + datetime.timedelta(
                    seconds=1 if v.microsecond >= 500_000 else 0)
            out[f"p{i + 1}"] = self._convert_in(v)
        return out

    # -- facade --

    def _run(self, sqlite_sql: str, params: dict):
        import sqlite3

        try:
            return self.db.execute(sqlite_sql, params)
        except sqlite3.IntegrityError as e:
            raise _map_sqlite_error(e) from e

    def fetch(self, sql: str, args: Sequence[Any] = ()) -> List[dict]:
        rows = self._run(self._translate(sql), self._params(args, sql)).fetchall()
        return [self._convert_out(r) for r in rows]

    def execute(self, sql: str, args: Sequence[Any] = ()) -> None:
        self._run(self._translate(sql), self._params(args, sql))

    def executemany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        """Row loop under an implicit transaction (when none is open) —
        asyncpg's executemany is atomic, and the backend relies on that
        (pg.py add_transactions); the mock must not be weaker."""
        sqlite_sql = self._translate(sql)
        own_txn = not self.db.in_transaction
        if own_txn:
            self.db.execute("BEGIN")
        try:
            for args in rows:
                self._run(sqlite_sql, self._params(args, sql))
        except BaseException:
            if own_txn:
                self.db.execute("ROLLBACK")
            raise
        else:
            if own_txn:
                self.db.execute("COMMIT")

    def begin(self) -> None:
        self.db.execute("BEGIN")

    def commit(self) -> None:
        self.db.execute("COMMIT")

    def rollback(self) -> None:
        self.db.execute("ROLLBACK")

    def close(self) -> None:
        self.db.close()

    # awaitable facade: same semantics, sqlite is in-process so the
    # "await" is immediate — what matters is interface parity with
    # AsyncpgDriver so PgChainState's SQL runs identically on both

    async def afetch(self, sql: str, args: Sequence[Any] = ()) -> List[dict]:
        return self.fetch(sql, args)

    async def aexecute(self, sql: str, args: Sequence[Any] = ()) -> None:
        self.execute(sql, args)

    async def aexecutemany(self, sql: str,
                           rows: Iterable[Sequence[Any]]) -> None:
        self.executemany(sql, rows)

    async def abegin(self) -> None:
        self.begin()

    async def acommit(self) -> None:
        self.commit()

    async def arollback(self) -> None:
        self.rollback()
