"""Drivers for the PostgreSQL chain-state backend (state/pg.py).

Two implementations of one small synchronous facade:

* :class:`AsyncpgDriver` — production: asyncpg (the reference's own
  driver, database.py:33-91) behind a dedicated event-loop thread, so
  the storage layer keeps the same short-synchronous-call model the
  sqlite backend uses.  asyncpg is imported lazily: it is not part of
  this framework's baseline dependencies and only needed when an
  operator points the node at a PostgreSQL uPow database.

* :class:`MockPgDriver` — tests: executes the same pg-dialect SQL
  against stdlib sqlite, translating ``$n`` placeholders and the
  handful of type-representation differences (TEXT[]/BIGINT[] arrays,
  NUMERIC, TIMESTAMP, BOOLEAN).  This is what lets the PgChainState SQL
  and conversion logic run under CI with no server; the identical suite
  runs against a real server when ``UPOW_PG_DSN`` is set.

The SQL subset the pg backend restricts itself to (so both drivers
behave identically): explicit column lists, ``$n`` parameters, whole
arrays as values (never indexed/ANY'd in SQL — the one exception,
``= ANY(col)``, is translated by the mock), no NOW() (timestamps are
passed in), row-value IN lists built with explicit placeholders.
"""

from __future__ import annotations

import datetime
import json
import re
import threading
from decimal import Decimal
from typing import Any, Iterable, List, Optional, Sequence


def _utc(dt_or_epoch) -> datetime.datetime:
    """Naive-UTC datetime (what the reference stores in TIMESTAMP(0)
    columns via datetime.utcfromtimestamp)."""
    if isinstance(dt_or_epoch, datetime.datetime):
        return dt_or_epoch
    return datetime.datetime.fromtimestamp(
        int(dt_or_epoch), datetime.timezone.utc).replace(tzinfo=None)


def _epoch(dt) -> int:
    if isinstance(dt, (int, float)):
        return int(dt)
    return int(dt.replace(tzinfo=datetime.timezone.utc).timestamp())


class AsyncpgDriver:
    """One asyncpg connection on a private loop thread, sync facade.

    Single-connection by design: the node's storage access is already
    serialized through its event loop (the sqlite backend is one
    connection too), and block acceptance wraps BEGIN/COMMIT around the
    connection — a pool would break that transaction affinity.

    Each call blocks the calling thread for one driver round trip —
    the same short-synchronous-call model the sqlite backend uses, but
    with a network RTT attached.  The storage layer batches its hot
    paths into executemany/JOIN shapes to keep statements-per-block
    low; deployments should colocate the node with the database (the
    reference's asyncpg setup assumes the same).
    """

    def __init__(self, dsn: str):
        import asyncio

        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True, name="pg-driver")
        self._thread.start()
        self._conn = self._call(self._connect(dsn))

    async def _connect(self, dsn: str):
        import asyncpg  # lazy: only a pg-backed node pays this import

        return await asyncpg.connect(dsn)

    def _call(self, coro):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result()

    def fetch(self, sql: str, args: Sequence[Any] = ()) -> List[Any]:
        return self._call(self._conn.fetch(sql, *args))

    def execute(self, sql: str, args: Sequence[Any] = ()) -> None:
        self._call(self._conn.execute(sql, *args))

    def executemany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        rows = list(rows)
        if rows:
            self._call(self._conn.executemany(sql, rows))

    def begin(self) -> None:
        self.execute("BEGIN")

    def commit(self) -> None:
        self.execute("COMMIT")

    def rollback(self) -> None:
        self.execute("ROLLBACK")

    def close(self) -> None:
        try:
            self._call(self._conn.close())
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)


# --- mock driver ---------------------------------------------------------

# Output-column representation map (reference schema.sql types).  The pg
# backend's SQL keeps these column names stable (including aliases), so
# name-based conversion is unambiguous.
_ARRAY_COLS = {"inputs_addresses", "outputs_addresses", "outputs_amounts"}
_NUMERIC_COLS = {"fees", "reward", "difficulty"}
_TIMESTAMP_COLS = {"timestamp", "propagation_time", "block_ts", "ts"}
_BOOL_COLS = {"is_stake"}

# sqlite DDL mirroring schema.sql's tables (same names, sqlite types);
# "index" is kept verbatim — sqlite accepts it quoted.
_MOCK_DDL = """
CREATE TABLE IF NOT EXISTS blocks (
    id INTEGER PRIMARY KEY,
    hash TEXT UNIQUE,
    content TEXT NOT NULL,
    address TEXT NOT NULL,
    random INTEGER NOT NULL,
    difficulty TEXT NOT NULL,
    reward TEXT NOT NULL,
    timestamp INTEGER
);
CREATE TABLE IF NOT EXISTS transactions (
    block_hash TEXT NOT NULL,
    tx_hash TEXT UNIQUE,
    tx_hex TEXT,
    inputs_addresses TEXT,
    outputs_addresses TEXT,
    outputs_amounts TEXT,
    fees TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS unspent_outputs (
    tx_hash TEXT,
    "index" INTEGER NOT NULL,
    address TEXT NULL,
    is_stake INTEGER
);
CREATE TABLE IF NOT EXISTS pending_transactions (
    tx_hash TEXT UNIQUE,
    tx_hex TEXT,
    inputs_addresses TEXT,
    fees TEXT NOT NULL,
    propagation_time INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS pending_spent_outputs (
    tx_hash TEXT,
    "index" INTEGER NOT NULL
);
"""
for _t in ("inode_registration_output", "validator_registration_output",
           "validators_voting_power", "delegates_voting_power",
           "validators_ballot", "inodes_ballot"):
    _MOCK_DDL += f"""
CREATE TABLE IF NOT EXISTS {_t} (
    tx_hash TEXT,
    "index" INTEGER NOT NULL,
    address TEXT NULL
);
"""

_PLACEHOLDER = re.compile(r"\$(\d+)")
_ANY_CLAUSE = re.compile(r"\$(\d+)\s*=\s*ANY\s*\(\s*(\w+)\s*\)")


class MockPgDriver:
    """sqlite stand-in executing the pg backend's SQL (tests only)."""

    supports_composite_types = False  # no CREATE TYPE in sqlite
    schema_preinstalled = True  # __init__ applies the sqlite-dialect DDL
    # (the reference-dialect PG_SCHEMA text is pg-only: it spells the
    # outpoint column as unquoted `index`, reserved in sqlite)

    def __init__(self):
        import sqlite3

        self.db = sqlite3.connect(":memory:")
        self.db.isolation_level = None  # autocommit; BEGIN/COMMIT explicit
        self.db.row_factory = sqlite3.Row
        self.db.executescript(_MOCK_DDL)

    # -- translation --

    @staticmethod
    def _convert_in(value):
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, list):
            return json.dumps(value)
        if isinstance(value, datetime.datetime):
            return _epoch(value)
        if isinstance(value, Decimal):
            return str(value)
        return value

    @staticmethod
    def _convert_out(row) -> dict:
        out = {}
        for key in row.keys():
            v = row[key]
            if v is None:
                out[key] = None
            elif key in _ARRAY_COLS:
                out[key] = json.loads(v)
            elif key in _NUMERIC_COLS:
                out[key] = Decimal(str(v))
            elif key in _TIMESTAMP_COLS:
                out[key] = _utc(v)
            elif key in _BOOL_COLS:
                out[key] = bool(v)
            else:
                out[key] = v
        return out

    @classmethod
    def _translate(cls, sql: str):
        """pg-dialect SQL -> (sqlite SQL using :pN named params)."""
        # `$k = ANY(col)`: pg array membership -> sqlite json_each scan
        sql = _ANY_CLAUSE.sub(
            r"EXISTS (SELECT 1 FROM json_each(\2) WHERE"
            r" json_each.value = :p\1)", sql)
        return _PLACEHOLDER.sub(r":p\1", sql)

    def _params(self, args: Sequence[Any]) -> dict:
        return {f"p{i + 1}": self._convert_in(v) for i, v in enumerate(args)}

    # -- facade --

    def fetch(self, sql: str, args: Sequence[Any] = ()) -> List[dict]:
        rows = self.db.execute(self._translate(sql), self._params(args)).fetchall()
        return [self._convert_out(r) for r in rows]

    def execute(self, sql: str, args: Sequence[Any] = ()) -> None:
        self.db.execute(self._translate(sql), self._params(args))

    def executemany(self, sql: str, rows: Iterable[Sequence[Any]]) -> None:
        sql = self._translate(sql)
        for args in rows:
            self.db.execute(sql, self._params(args))

    def begin(self) -> None:
        self.db.execute("BEGIN")

    def commit(self) -> None:
        self.db.execute("COMMIT")

    def rollback(self) -> None:
        self.db.execute("ROLLBACK")

    def close(self) -> None:
        self.db.close()
