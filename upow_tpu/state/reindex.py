"""UTXO reindex tool (reference create_unspent_outputs.py:37-41).

    python -m upow_tpu.state.reindex [--db PATH | --pg-dsn DSN] [--check]

Rebuilds every UTXO-class table by replaying the transaction log in
block order.  ``--check`` compares the full state fingerprint (all six
UTXO-class tables, not just the wire-visible unspent_outputs hash)
without touching the live database — the consensus-bug detector the
reference runs in production (SURVEY.md §4 oracles).  On sqlite the
check replays a backup copy; on PostgreSQL it replays inside one
transaction and rolls it back.
"""

from __future__ import annotations

import argparse
import asyncio
import shutil
import sqlite3
import sys
import tempfile

from ..config import Config
from .storage import ChainState


async def check_replay_pg(state) -> tuple:
    """(before, after) full-state fingerprints, replaying inside one
    rolled-back transaction — the live tables are never modified."""
    before = await state.get_full_state_hash()
    async with state.replay_transaction():
        await state.rebuild_utxos()
        after = await state.get_full_state_hash()
    return before, after


async def amain(argv=None) -> int:
    ap = argparse.ArgumentParser("upow_tpu reindex")
    ap.add_argument("--db", default=None, help="chain sqlite path")
    ap.add_argument("--pg-dsn", default=None,
                    help="PostgreSQL DSN (reference schema.sql database)")
    ap.add_argument("--check", action="store_true",
                    help="verify only: replay a copy, compare fingerprints")
    args = ap.parse_args(argv)

    cfg = Config.load()
    # an explicit --db targets a sqlite file even when the config is
    # postgres-backed (offline snapshot checks must never touch the
    # live pg database)
    pg_dsn = args.pg_dsn if args.pg_dsn is not None else (
        cfg.node.pg_dsn
        if cfg.node.db_backend == "postgres" and args.db is None else "")
    if pg_dsn:
        from .pg import PgChainState

        state = PgChainState(pg_dsn)
        try:
            blocks = await state.get_next_block_id() - 1
            if args.check:
                before, after = await check_replay_pg(state)
            else:
                before = await state.get_full_state_hash()
                await state.rebuild_utxos()
                after = await state.get_full_state_hash()
            print(f"{blocks} blocks; live state fingerprint {before}")
            print(f"replayed state fingerprint {after}")
            if args.check and after != before:
                print("MISMATCH: live UTXO-class tables diverge from the "
                      "tx log (consensus bug or corruption)")
                return 1
            if args.check:
                print("OK: live tables match the replay")
            return 0
        finally:
            state.close()

    db_path = args.db if args.db is not None else cfg.node.db_path
    if not db_path:
        print("no database configured (--db / --pg-dsn or "
              "UPOW_NODE_DB_PATH / UPOW_NODE_PG_DSN)")
        return 2

    work_path = db_path
    tmpdir = None
    if args.check:
        # replay into a COPY: a mismatch must leave the live tables
        # untouched as evidence, not overwrite them with the replay
        tmpdir = tempfile.mkdtemp(prefix="upow_reindex_")
        work_path = f"{tmpdir}/check.sqlite"
        src = sqlite3.connect(db_path)
        dst = sqlite3.connect(work_path)
        src.backup(dst)
        src.close()
        dst.close()

    state = ChainState(work_path)
    try:
        before = await state.get_full_state_hash()
        blocks = await state.get_next_block_id() - 1
        print(f"{blocks} blocks; live state fingerprint {before}")
        await state.rebuild_utxos()
        after = await state.get_full_state_hash()
        print(f"replayed state fingerprint {after}")
        if args.check and after != before:
            print("MISMATCH: live UTXO-class tables diverge from the tx "
                  "log (consensus bug or corruption)")
            return 1
        if args.check:
            print("OK: live tables match the replay")
        return 0
    finally:
        state.close()
        if tmpdir is not None:
            # RC001: offline maintenance CLI — nothing else shares
            # this event loop while it tears down
            shutil.rmtree(tmpdir, ignore_errors=True)  # upowlint: disable=RC001


def main() -> int:
    return asyncio.run(amain())


if __name__ == "__main__":
    sys.exit(main())
