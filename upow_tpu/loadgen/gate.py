"""Bench regression gate (stdlib only — runs without jax/aiohttp).

    python -m upow_tpu.loadgen.gate --against BENCH_r05.json \\
        [--current observatory.json] [--tolerance 0.25] [--report-only]

Flattens both sides into ``{metric: value}`` — understanding the
driver's BENCH capture wrapper (``{n, cmd, rc, tail, parsed}``),
bench.py single lines (with nested ``verify`` / ``native_cpu_allcores``
sub-metrics), bench_suite JSON-line streams, and observatory artifacts
(``slo.endpoints`` + ``kernels``) — then compares every metric present
on BOTH sides.

Direction: a metric entry may carry an explicit
``"direction": "higher" | "lower"`` in the artifact (kernel entries and
bench lines), which always wins.  Otherwise direction is inferred from
the name: latency-like metrics (``*_ms``, ``p50/p95/p99``,
``*latency*``, ``*seconds*``) regress upward, throughput metrics
regress downward — name inference is ambiguous for names like
``verify_pipeline_speedup`` vs ``dispatch_seconds``, which is exactly
what the explicit override exists for.  A metric regresses when it is
worse than baseline by more than ``--tolerance`` (relative).

``--metric-tolerance NAME=TOL`` (repeatable) pins an exact flattened
metric name to its own tolerance; ``--enforce SUBSTR`` (repeatable)
promotes matching metrics from report-only to enforced — a regression
on one fails the gate even under ``--report-only`` (how ``make
perf-smoke`` keeps its advisory report while hard-gating the verify
pipeline and resident accept kernels).

``--trend PROGRESS.jsonl`` switches to trend-report mode: every
``perf_observatory`` line in the trajectory file (driver records with
other kinds are skipped) becomes one sample per metric, and the report
carries direction-aware per-metric trend lines (first → last, best /
worst, improving / regressing / flat).  Trend mode never fails the
build — it is a trajectory report, not a gate.

Exit codes: 0 ok / report-only / trend, 1 regression(s), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

DEFAULT_TOLERANCE = 0.25

_LOWER_BETTER_TOKENS = ("_ms", "latency", "p50", "p95", "p99",
                        "seconds", "_errors")


def lower_is_better(metric: str) -> bool:
    m = metric.lower()
    return any(tok in m for tok in _LOWER_BETTER_TOKENS)


def _note_direction(directions: Optional[Dict[str, str]], name: str,
                    entry) -> None:
    """Record an entry's explicit ``direction`` field, if present and
    well-formed (anything else keeps name inference)."""
    if directions is None or not isinstance(entry, dict):
        return
    d = entry.get("direction")
    if d in ("higher", "lower"):
        directions[name] = d


def _num(value) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def flatten(doc: dict, prefix: str = "",
            directions: Optional[Dict[str, str]] = None) -> Dict[str, float]:
    """Extract comparable metrics from any of the known artifact
    shapes.  Unknown keys are ignored, never guessed at.  When a
    ``directions`` dict is passed, explicit per-metric ``direction``
    fields found in the artifact are collected into it."""
    out: Dict[str, float] = {}
    if not isinstance(doc, dict):
        return out

    # driver capture wrapper: the real content lives under "parsed"
    if isinstance(doc.get("parsed"), dict):
        out.update(flatten(doc["parsed"], prefix, directions))

    # bench.py / bench_suite line: {"metric": ..., "value": ...}
    metric, value = doc.get("metric"), _num(doc.get("value"))
    if isinstance(metric, str) and value is not None:
        out[prefix + metric] = value
        _note_direction(directions, prefix + metric, doc)
    for key in ("verify", "native_cpu_allcores"):
        sub = doc.get(key)
        if isinstance(sub, dict):
            sub_metric = sub.get("metric", key)
            sub_value = _num(sub.get("value"))
            if sub_value is not None:
                out[prefix + str(sub_metric)] = sub_value
                _note_direction(directions, prefix + str(sub_metric), sub)

    # observatory artifact
    slo = doc.get("slo")
    if isinstance(slo, dict):
        for ep, row in (slo.get("endpoints") or {}).items():
            if not isinstance(row, dict):
                continue
            for field in ("req_s", "p50_ms", "p95_ms", "p99_ms"):
                v = _num(row.get(field))
                if v is not None:
                    out[f"{prefix}slo.{ep}.{field}"] = v
    kernels = doc.get("kernels")
    if isinstance(kernels, dict):
        for name, entry in kernels.items():
            if name == "last_good_tpu":
                continue  # stale snapshots must not gate a live run
            v = _num(entry.get("value")) if isinstance(entry, dict) \
                else _num(entry)
            if v is not None:
                out[f"{prefix}kernel.{name}"] = v
                _note_direction(directions, f"{prefix}kernel.{name}", entry)
    return out


def load_metrics(path: str,
                 directions: Optional[Dict[str, str]] = None
                 ) -> Dict[str, float]:
    """Flatten a file that is one JSON document or a JSON-line stream
    (bench_suite output); later lines win on metric collisions."""
    with open(path) as f:
        text = f.read()
    try:
        return flatten(json.loads(text), directions=directions)
    except ValueError:
        out: Dict[str, float] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                out.update(flatten(json.loads(line), directions=directions))
            except ValueError:
                continue  # interleaved log noise
        return out


def compare(baseline: Dict[str, float], current: Dict[str, float],
            tolerance: float,
            directions: Optional[Dict[str, str]] = None,
            metric_tolerances: Optional[Dict[str, float]] = None
            ) -> List[dict]:
    """Per-common-metric verdicts, regressions first.  ``directions``
    carries the artifacts' explicit per-metric overrides; metrics
    without one fall back to name inference.  ``metric_tolerances``
    maps exact metric names to a tolerance that replaces the global one
    for that metric (``--metric-tolerance NAME=TOL``)."""
    directions = directions or {}
    metric_tolerances = metric_tolerances or {}
    rows = []
    for metric in sorted(set(baseline) & set(current)):
        base, cur = baseline[metric], current[metric]
        tol = metric_tolerances.get(metric, tolerance)
        override = directions.get(metric)
        lower = (override == "lower") if override \
            else lower_is_better(metric)
        if base == 0:
            regressed = lower and cur > 0 and tol < 1
            ratio = None
        else:
            ratio = cur / base
            regressed = (ratio > 1 + tol if lower
                         else ratio < 1 - tol)
        rows.append({"metric": metric, "baseline": base, "current": cur,
                     "ratio": round(ratio, 4) if ratio is not None else None,
                     "direction": "lower" if lower else "higher",
                     "direction_source": "artifact" if override
                     else "inferred",
                     "tolerance": tol,
                     "regressed": regressed})
    rows.sort(key=lambda r: (not r["regressed"], r["metric"]))
    return rows


def _flatten_progress_line(line: dict) -> Dict[str, float]:
    """Flatten one PROGRESS.jsonl ``perf_observatory`` line (its slo
    block is ``{ep: row}`` without the artifact's ``endpoints``
    wrapper, and its kernels are plain values)."""
    out: Dict[str, float] = {}
    for ep, row in (line.get("slo") or {}).items():
        if not isinstance(row, dict):
            continue
        for field in ("req_s", "p50_ms", "p95_ms", "p99_ms"):
            v = _num(row.get(field))
            if v is not None:
                out[f"slo.{ep}.{field}"] = v
    for name, value in (line.get("kernels") or {}).items():
        if name == "last_good_tpu":
            continue
        v = _num(value)
        if v is not None:
            out[f"kernel.{name}"] = v
    return out


def trend_report(path: str) -> dict:
    """Direction-aware per-metric trajectory over a PROGRESS.jsonl
    history.  Non-observatory lines (the driver's own records share the
    file) are skipped by ``kind``."""
    samples: List[Dict[str, float]] = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue  # interleaved log noise
            if not isinstance(line, dict) \
                    or line.get("kind") != "perf_observatory":
                continue
            flat = _flatten_progress_line(line)
            if flat:
                samples.append(flat)

    series: Dict[str, List[float]] = {}
    for flat in samples:
        for metric, value in flat.items():
            series.setdefault(metric, []).append(value)

    rows = []
    for metric in sorted(series):
        vals = series[metric]
        first, last = vals[0], vals[-1]
        lower = lower_is_better(metric)
        if first == 0:
            change_pct = None
            verdict = "flat" if last == 0 else (
                "regressing" if lower else "improving")
        else:
            change = (last - first) / abs(first)
            change_pct = round(change * 100.0, 2)
            if abs(change) < 0.02:
                verdict = "flat"
            elif (change > 0) != lower:
                verdict = "improving"
            else:
                verdict = "regressing"
        rows.append({
            "metric": metric,
            "samples": len(vals),
            "first": first, "last": last,
            "best": min(vals) if lower else max(vals),
            "worst": max(vals) if lower else min(vals),
            "direction": "lower" if lower else "higher",
            "change_pct": change_pct,
            "trend": verdict,
        })
    order = {"regressing": 0, "flat": 1, "improving": 2}
    rows.sort(key=lambda r: (order[r["trend"]], r["metric"]))
    return {"kind": "trend_report", "progress": path,
            "observatory_lines": len(samples), "metrics": rows}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m upow_tpu.loadgen.gate",
        description="Fail when a metric regresses beyond tolerance.")
    ap.add_argument("--against",
                    help="baseline artifact (BENCH_r*.json, bench_suite "
                         "stream, or observatory.json)")
    ap.add_argument("--trend", metavar="PROGRESS_JSONL",
                    help="report per-metric trend lines over a "
                         "PROGRESS.jsonl history instead of gating "
                         "(always exits 0)")
    ap.add_argument("--current", default="observatory.json",
                    help="current artifact (default: observatory.json)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="relative band before a worse value fails "
                         f"(default {DEFAULT_TOLERANCE})")
    ap.add_argument("--report-only", action="store_true",
                    help="print verdicts but always exit 0 (except for "
                         "--enforce'd metrics)")
    ap.add_argument("--metric-tolerance", action="append", default=[],
                    metavar="NAME=TOL",
                    help="per-metric tolerance overriding --tolerance "
                         "(exact flattened name, repeatable)")
    ap.add_argument("--enforce", action="append", default=[],
                    metavar="SUBSTR",
                    help="metrics whose flattened name contains SUBSTR "
                         "fail the gate even under --report-only "
                         "(repeatable)")
    args = ap.parse_args(argv)

    if args.trend:
        try:
            report = trend_report(args.trend)
        except OSError as e:
            print(f"gate: cannot read progress file: {e}",
                  file=sys.stderr)
            return 2
        print(json.dumps(report, indent=1, sort_keys=True))
        for r in report["metrics"]:
            if r["trend"] != "flat":
                pct = f"{r['change_pct']:+}%" \
                    if r["change_pct"] is not None else "n/a"
                print(f"trend: {r['trend']:>10} {r['metric']} "
                      f"{r['first']} -> {r['last']} ({pct}, "
                      f"{r['direction']} is better)", file=sys.stderr)
        return 0

    if not args.against:
        ap.error("--against is required (unless --trend)")

    metric_tolerances: Dict[str, float] = {}
    for spec in args.metric_tolerance:
        name, sep, tol = spec.partition("=")
        if not sep or not name:
            print(f"gate: bad --metric-tolerance {spec!r} "
                  "(want NAME=TOL)", file=sys.stderr)
            return 2
        try:
            metric_tolerances[name] = float(tol)
        except ValueError:
            print(f"gate: bad --metric-tolerance value {tol!r}",
                  file=sys.stderr)
            return 2

    # direction overrides merge across both artifacts; the current one
    # wins (it carries the newest metadata for renamed/retyped metrics)
    directions: Dict[str, str] = {}
    try:
        baseline = load_metrics(args.against, directions)
        current = load_metrics(args.current, directions)
    except OSError as e:
        print(f"gate: cannot read artifact: {e}", file=sys.stderr)
        return 2
    if not baseline or not current:
        print("gate: no metrics found in "
              + (args.against if not baseline else args.current),
              file=sys.stderr)
        return 2

    rows = compare(baseline, current, args.tolerance, directions,
                   metric_tolerances)
    regressions = [r for r in rows if r["regressed"]]
    enforced = [r for r in regressions
                if any(s in r["metric"] for s in args.enforce)]
    report = {
        "against": args.against, "current": args.current,
        "tolerance": args.tolerance,
        "metric_tolerances": metric_tolerances,
        "enforce": args.enforce,
        "compared": len(rows), "regressions": len(regressions),
        "enforced_regressions": len(enforced),
        "verdicts": rows,
    }
    print(json.dumps(report, indent=1, sort_keys=True))
    if not rows:
        print("gate: WARNING no overlapping metrics between artifacts",
              file=sys.stderr)
        return 0
    failing = enforced if args.report_only else regressions
    if failing:
        for r in failing:
            print(f"gate: REGRESSION {r['metric']}: "
                  f"{r['baseline']} -> {r['current']} "
                  f"({r['direction']} is better, tol {r['tolerance']})"
                  + (" [enforced]" if r in enforced else ""),
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
