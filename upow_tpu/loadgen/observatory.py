"""The perf observatory: one artifact merging SLO + kernel metrics.

``python -m upow_tpu.loadgen`` (and ``make perf-observatory`` /
bench_suite config 11) runs the load generator against the in-process
node, measures the cheap host-path kernel benches, and writes a single
structured JSON artifact:

* ``slo`` — per-endpoint req/s + p50/p95/p99 (client-measured, exact)
  plus the node's own server-side histogram estimates.
* ``kernels`` — host kernel rates (python / native search + verify)
  and, when armed, the freshest persisted TPU capture.
* ``readpath`` — the hot-state cache scenario (:mod:`.readpath`):
  cached vs bypassed p99 under block cadence, with its byte-identity
  differential; headline metrics are mirrored into ``kernels`` with
  explicit gate directions.
* ``coresidency`` — the shared device-runtime scenario
  (:mod:`.coresidency`): miner + block verify + mempool intake on one
  runtime, cross-source coalescing and fairness deltas with the same
  differential-gated mirroring into ``kernels``.
* ``fleet`` — the deterministic geo-soak (:mod:`..fleet.geosoak`):
  cross-node propagation percentiles, the stitched push_tx trace
  span, and ``fleet_core_ok`` mirrored into ``kernels`` with the
  propagation quantiles (zeroed on any core assertion failure so the
  enforced gate trips on broken distribution semantics).
* ``archive`` — the cold-block archival differential
  (:mod:`..archive.parity`): the archive_prune scenario's pruned node
  vs unpruned twin byte parity, with ``archive_parity_ok`` mirrored
  into ``kernels`` (zeroed on any divergence so the enforced gate
  trips on a broken hot/archive seam, same idiom as
  ``fleet_core_ok``).
* ``provenance`` — what actually ran: ``backend``, ``platform``,
  ``attempted_backend``, ``arm_failure_reason``, ``arm_attempt``
  (which arm attempt produced this process — ``runtime`` /
  ``cpu-child`` / ... — via bench.py's env contract in benchutil).
  BENCH_r02–r05 all silently degraded to a scrubbed-env CPU child;
  this block is the machine-readable record that it happened (or
  didn't).
* optionally appended (``--progress``) to PROGRESS.jsonl so the
  trajectory file carries SLO metrics alongside kernel throughput.

The regression gate (:mod:`.gate`) consumes these artifacts.
"""

from __future__ import annotations

import asyncio
import datetime
import json
import os
from typing import Optional

from ..logger import get_logger
from .population import PopulationSpec, build_schedule, schedule_fingerprint

log = get_logger("loadgen")


def kernel_bench(seconds: float = 0.4) -> dict:
    """Cheap, always-available host kernel measurements (no XLA
    compiles — CI smoke must stay fast): the pure-python reference
    loops plus the native C++ paths when the extension is present."""
    from .. import native
    from ..benchutil import (python_loop_mhs, python_verify_rate,
                             timed_reps, verify_fixture)

    prefix = bytes(range(32)) * 2
    out = {
        "search_python_loop": {
            "value": round(python_loop_mhs(prefix, seconds), 3),
            "unit": "MH/s"},
    }
    digests, sigs, pubs, msgs = verify_fixture(512)
    out["verify_python"] = {
        "value": round(python_verify_rate(msgs, sigs, pubs, seconds), 1),
        "unit": "sigs/s"}
    if native.load() is not None:
        first = native.p256_verify_batch(digests, sigs, pubs)  # warm
        if first is not None and all(first):
            reps, elapsed = timed_reps(
                lambda: native.p256_verify_batch(digests, sigs, pubs),
                seconds)
            out["verify_native"] = {
                "value": round(reps * len(digests) / elapsed, 1),
                "unit": "sigs/s"}
    try:
        from ..benchutil import verify_pipeline_bench

        vp = verify_pipeline_bench(seconds=min(seconds, 0.4))
        # explicit direction overrides (consumed by gate.py): the
        # speedup/rate names don't match its latency-token inference
        out["verify_pipeline"] = {
            "value": round(vp["pipelined_tx_s"], 1), "unit": "tx/s",
            "direction": "higher",
            "verdicts_equal": vp["verdicts_equal"],
            "differential_txs": vp["differential_txs"]}
        out["verify_pipeline_serial"] = {
            "value": round(vp["serial_tx_s"], 1), "unit": "tx/s",
            "direction": "higher"}
        out["verify_pipeline_speedup"] = {
            "value": round(vp["speedup"], 2) if vp["verdicts_equal"]
            else 0.0,  # divergence zeroes the headline so the gate trips
            "unit": "x", "direction": "higher"}
    except Exception as e:
        log.warning("verify_pipeline bench skipped: %s", e)
    try:
        from ..benchutil import accept_resident_bench

        # smoke-sized chain (the full 8k block belongs to bench_suite
        # config 15); the differential contract is identical, and a
        # divergence zeroes both speedups so the gate trips
        ar = accept_resident_bench(seconds=min(seconds, 0.4),
                                   n_fan=16, n_per=8)
        out["accept_resident"] = {
            "value": ar["resident_tx_s"], "unit": "tx/s",
            "direction": "higher",
            "differential_ok": ar["differential_ok"],
            "shadow_consults": ar["shadow_consults"]}
        out["accept_serial"] = {
            "value": ar["serial_tx_s"], "unit": "tx/s",
            "direction": "higher"}
        out["accept_scan_speedup"] = {
            "value": ar["scan_speedup"], "unit": "x",
            "direction": "higher"}
    except Exception as e:
        log.warning("accept_resident bench skipped: %s", e)
    try:
        from ..benchutil import mining_mesh_bench

        # smoke-sized rounds on whatever mesh is visible (one device on
        # a plain CPU host; the 8-shard case is CI's mesh job).  A
        # diverged differential zeroes the sharded headline and the
        # speedup so the enforced gate trips on correctness breaks.
        mm = mining_mesh_bench(seconds=min(seconds, 0.4),
                               batch_per_device=1 << 12)
        out["mine_mesh_sharded"] = {
            "value": mm["sharded_mhs"], "unit": "MH/s",
            "direction": "higher",
            "differential_ok": mm["differential_ok"],
            "differential_checks": mm["differential_checks"],
            "n_devices": mm["n_devices"]}
        out["mine_mesh_serial"] = {
            "value": mm["serial_mhs"], "unit": "MH/s",
            "direction": "higher"}
        out["mine_mesh_speedup"] = {
            "value": mm["speedup"], "unit": "x", "direction": "higher"}
    except Exception as e:
        log.warning("mining_mesh bench skipped: %s", e)
    return out


def _arm_device(probe_timeout: float) -> dict:
    """Try to arm a real accelerator; provenance either way, plus the
    structured ``bench_arm_failed`` event on failure (satellite 1's
    contract, shared with bench.py)."""
    from .. import telemetry
    from ..benchutil import probed_platform_cached

    platform = probed_platform_cached(probe_timeout)
    if platform is None:
        reason = f"backend probe hung/failed after {probe_timeout:.0f}s"
        telemetry.event("bench_arm_failed", reason=reason,
                        attempted_backend="tpu", source="observatory")
        return {"platform": None, "attempted_backend": "tpu",
                "arm_failure_reason": reason}
    if platform == "cpu":
        reason = "only cpu visible to jax"
        telemetry.event("bench_arm_failed", reason=reason,
                        attempted_backend="tpu", source="observatory")
        return {"platform": "cpu", "attempted_backend": "tpu",
                "arm_failure_reason": reason}
    return {"platform": platform, "attempted_backend": "tpu",
            "arm_failure_reason": None}


def _kernel_cost_analysis() -> Optional[dict]:
    """Record the XLA cost analysis of the production jnp search
    program at a small batch (compile on whatever backend is armed)."""
    from .. import profiling
    from ..core import curve, point_to_string
    from ..core.header import BlockHeader
    from ..core.merkle import merkle_root
    from ..crypto import make_template, target_spec
    from ..crypto import sha256 as sk

    import jax.numpy as jnp

    _, pub = curve.keygen(rng=0xBE7C)
    header = BlockHeader(
        previous_hash=bytes(range(32)).hex(), address=point_to_string(pub),
        merkle_root=merkle_root([]), timestamp=1_753_791_000,
        difficulty_x10=90, nonce=0)
    template = make_template(header.prefix_bytes())
    spec = target_spec(header.previous_hash, "9.0")
    batch = 1 << 10
    return profiling.analyze_cost(
        f"sha256_pow_search_jnp_b{batch}", sk._pow_search_jnp,
        jnp.asarray(template.midstate), jnp.asarray(template.tail_words),
        jnp.uint32(0), batch, template.nonce_spec, spec)


def run_observatory(spec: Optional[PopulationSpec] = None,
                    bench_seconds: float = 0.4,
                    device: bool = False,
                    cost: bool = False,
                    probe_timeout: float = 90.0,
                    readpath_spec=None,
                    coresidency_spec=None) -> dict:
    """Run loadgen + kernel benches; return the merged artifact."""
    from .harness import run_against_node

    spec = spec or PopulationSpec()
    provenance = {"backend": "node-inprocess", "platform": "host",
                  "attempted_backend": None, "arm_failure_reason": None,
                  "arm_attempt": None}
    if device:
        provenance.update(_arm_device(probe_timeout))
    # overlay the arm story bench.py's env contract carries (scrubbed
    # CPU child, runtime re-arm, ...) — only the keys actually set, so
    # a plain observatory run keeps its own probe-derived provenance
    from ..benchutil import arm_provenance_from_env

    provenance.update({k: v for k, v in
                       arm_provenance_from_env().items() if v is not None})

    load = asyncio.run(run_against_node(spec))
    kernels = kernel_bench(bench_seconds)

    readpath = None
    try:
        from .readpath import ReadpathSpec, run_readpath

        readpath = asyncio.run(run_readpath(readpath_spec
                                            or ReadpathSpec()))
    except Exception as e:
        log.warning("readpath scenario skipped: %s", e)
    if readpath is not None:
        diff_ok = readpath["differential"]["ok"]
        # divergence zeroes the headline (run_readpath already refused
        # to report latencies); the explicit direction keeps gate.py
        # from latency-token-inferring "lower" off the _p99 suffix
        kernels["readpath_speedup_p99"] = {
            "value": readpath["speedup_p99"] or 0.0, "unit": "x",
            "direction": "higher", "differential_ok": diff_ok,
            "differential_checks": readpath["differential"]["checks"]}
        if diff_ok:
            kernels["readpath_bypass_p99_ms"] = {
                "value": readpath["bypass"]["p99_ms"], "unit": "ms",
                "direction": "lower"}
            kernels["readpath_cached_p99_ms"] = {
                "value": readpath["cached"]["p99_ms"], "unit": "ms",
                "direction": "lower"}
            kernels["readpath_hit_ratio"] = {
                "value": readpath["cached_pass"]["hit_ratio"],
                "unit": "ratio", "direction": "higher"}

    coresidency = None
    try:
        from .coresidency import CoresidencySpec, run_coresidency

        coresidency = run_coresidency(coresidency_spec
                                      or CoresidencySpec.smoke())
    except Exception as e:
        log.warning("coresidency scenario skipped: %s", e)
    if coresidency is not None:
        co_ok = coresidency["differential"]["ok"]
        # same convention as readpath: divergence already zeroed the
        # headline and withheld the perf sections; the explicit
        # directions keep gate.py's token inference out of it
        kernels["coresidency_coalesce_ratio"] = {
            "value": coresidency["coalesce_ratio"] or 0.0, "unit": "x",
            "direction": "higher", "differential_ok": co_ok,
            "differential_checks": coresidency["differential"]["checks"]}
        if co_ok:
            conc = coresidency["concurrent"]
            kernels["coresidency_dispatch_reduction"] = {
                "value": coresidency["dispatch_reduction"], "unit": "x",
                "direction": "higher"}
            kernels["coresidency_occupancy"] = {
                "value": conc["occupancy"] or 0.0, "unit": "ratio",
                "direction": "higher"}
            kernels["coresidency_verify_wait_p99_ms"] = {
                "value": conc["verify_wait_p99_ms"], "unit": "ms",
                "direction": "lower"}

    fleet = None
    try:
        from ..fleet.geosoak import observatory_section

        fleet = observatory_section()
    except Exception as e:
        log.warning("fleet geo-soak skipped: %s", e)
    if fleet is not None:
        # direction-annotated rows (fleet_core_ok zeroes on any failed
        # core assertion, defeating any gate tolerance — same idiom as
        # the differential-zeroed kernel headlines above)
        kernels.update(fleet["kernels"])

    archive = None
    try:
        from ..archive.parity import observatory_section \
            as archive_section

        archive = archive_section()
    except Exception as e:
        log.warning("archive parity differential skipped: %s", e)
    if archive is not None:
        # archive_parity_ok zeroes on ANY failed core assertion in the
        # pruned-vs-twin scenario, defeating any gate tolerance
        kernels.update(archive["kernels"])

    if cost:
        try:
            analysis = _kernel_cost_analysis()
            if analysis:
                kernels["search_jnp_cost_analysis"] = {
                    k: analysis[k] for k in sorted(analysis)[:8]}
        except Exception as e:
            log.warning("cost analysis skipped: %s", e)

    try:
        from bench import _load_last_good_tpu  # repo-root bench.py

        last_good = _load_last_good_tpu()
    except Exception as e:  # installed-package runs have no bench.py
        log.debug("last_good_tpu snapshot unavailable: %s", e)
        last_good = None
    if last_good:
        kernels["last_good_tpu"] = {
            metric: {"value": entry.get("value"),
                     "unit": entry.get("unit"),
                     "measured_at": entry.get("measured_at")}
            for metric, entry in last_good.items()}

    artifact = {
        "kind": "perf_observatory",
        "schedule_fingerprint": schedule_fingerprint(build_schedule(spec)),
        "population": spec.to_dict(),
        "slo": {
            "elapsed_s": load["elapsed_s"],
            "events": load["events"],
            "endpoints": load["endpoints"],
            "server_estimates": load.get("server_slo", {}),
        },
        "ws": load.get("ws_hub", {}),
        "kernels": kernels,
        "provenance": provenance,
    }
    if readpath is not None:
        artifact["readpath"] = readpath
    if coresidency is not None:
        artifact["coresidency"] = coresidency
    if fleet is not None:
        artifact["fleet"] = fleet["section"]
        # per-node fleet latency rows + propagation quantile rows ride
        # the endpoint table (names are fleet.-prefixed: no collisions)
        artifact["slo"]["endpoints"].update(fleet["slo_endpoints"])
    if archive is not None:
        artifact["archive"] = archive["section"]
        artifact["slo"]["endpoints"].update(archive["slo_endpoints"])
    return artifact


def write_artifact(artifact: dict, out_path: str) -> None:
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out_path)


def append_progress(artifact: dict, progress_path: str) -> None:
    """One compact trajectory line per observatory run, additive to the
    driver's own PROGRESS.jsonl records (distinguished by ``kind``)."""
    line = {
        "ts": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "kind": "perf_observatory",
        "slo": {ep: {"req_s": row.get("req_s"),
                     "p50_ms": row.get("p50_ms"),
                     "p95_ms": row.get("p95_ms"),
                     "p99_ms": row.get("p99_ms"),
                     "errors": row.get("errors")}
                for ep, row in artifact["slo"]["endpoints"].items()},
        "kernels": {name: entry.get("value")
                    for name, entry in artifact["kernels"].items()
                    if isinstance(entry, dict) and "value" in entry},
        "provenance": artifact["provenance"],
    }
    with open(progress_path, "a") as f:
        f.write(json.dumps(line, sort_keys=True) + "\n")
