"""Seeded wallet-population request schedules (stdlib only).

A population is a set of actor streams, each with its own derived RNG
so the merged schedule is a pure function of the spec:

* **readers** — wallet clients polling balances / UTXO sets / history
  for Zipf-distributed addresses (a few hot accounts absorb most
  reads, the long tail the rest — the shape real explorers see).
* **miners** — ``get_mining_info`` template polling.
* **pushers** — bursts of simultaneous ``push_tx`` submissions, sized
  to exercise the mempool intake's micro-batch coalescing.
* **ws subscribers** — connect / subscribe / ping / close churn
  against the ``/ws`` hub.

Events carry abstract indices (``wallet``, ``payload``, ``conn``) —
the executor (mock or real-node harness) maps them to addresses, tx
payloads and sockets.  Same seed → byte-identical schedule; the
determinism test pins this.
"""

from __future__ import annotations

import bisect
import random
from dataclasses import dataclass, fields
from typing import Dict, List, Tuple

#: event kind -> HTTP endpoint (ws kinds map to the pseudo-endpoint
#: "ws"; summaries group by this name)
ENDPOINTS = {
    "balance": "/get_address_info",
    "utxo": "/get_address_info",
    "history": "/get_address_transactions",
    "mining_info": "/get_mining_info",
    "push_tx": "/push_tx",
    "ws_connect": "ws",
    "ws_ping": "ws",
    "ws_close": "ws",
}


@dataclass(frozen=True)
class LoadEvent:
    at: float                 # virtual seconds from schedule start
    seq: int                  # stable identity / sort tiebreak
    kind: str
    params: Tuple[Tuple[str, object], ...] = ()

    @property
    def endpoint(self) -> str:
        return ENDPOINTS[self.kind]

    def param(self, name: str, default=None):
        for k, v in self.params:
            if k == name:
                return v
        return default


@dataclass
class PopulationSpec:
    """Knobs for one synthetic wallet population."""

    seed: int = 0xC0FFEE
    duration: float = 2.0      # virtual schedule length (seconds)
    n_wallets: int = 256       # address universe the readers draw from
    zipf_s: float = 1.1        # skew: ~1 mild, 2 one-account-dominates
    n_readers: int = 8
    reader_rps: float = 25.0   # per-reader mean poll rate
    n_miners: int = 2
    miner_rps: float = 10.0
    n_ws: int = 4              # websocket subscribers
    ws_churn: int = 2          # connect/close cycles per subscriber
    push_bursts: int = 4
    burst_size: int = 16       # concurrent push_tx per burst

    @classmethod
    def smoke(cls, seed: int = 0xC0FFEE) -> "PopulationSpec":
        """Tiny population for CI: finishes in a few seconds on CPU."""
        return cls(seed=seed, duration=1.0, n_wallets=32, n_readers=3,
                   reader_rps=12.0, n_miners=1, miner_rps=6.0, n_ws=2,
                   ws_churn=1, push_bursts=2, burst_size=8)

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}


def zipf_cdf(n: int, s: float) -> List[float]:
    """Cumulative distribution of Zipf(s) over ranks 1..n."""
    weights = [1.0 / (rank ** s) for rank in range(1, n + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    cdf[-1] = 1.0
    return cdf


def pick_zipf(rng: random.Random, cdf: List[float]) -> int:
    """Rank index (0 = hottest) drawn from a precomputed CDF."""
    return bisect.bisect_left(cdf, rng.random())


def _rng(spec: PopulationSpec, stream: str, idx: int) -> random.Random:
    # one independent RNG per actor stream: inserting a new stream
    # cannot shift the draws of existing ones
    return random.Random(f"{spec.seed}:{stream}:{idx}")


def build_schedule(spec: PopulationSpec) -> List[LoadEvent]:
    """Merged, time-sorted event list for the population."""
    raw: List[Tuple[float, str, Tuple[Tuple[str, object], ...]]] = []
    cdf = zipf_cdf(spec.n_wallets, spec.zipf_s)

    for r in range(spec.n_readers):
        rng = _rng(spec, "reader", r)
        t = rng.random() / max(spec.reader_rps, 1e-9)
        while t < spec.duration:
            roll = rng.random()
            kind = ("balance" if roll < 0.6
                    else "utxo" if roll < 0.85 else "history")
            raw.append((t, kind, (("wallet", pick_zipf(rng, cdf)),)))
            t += rng.expovariate(spec.reader_rps)

    for m in range(spec.n_miners):
        rng = _rng(spec, "miner", m)
        t = rng.random() / max(spec.miner_rps, 1e-9)
        while t < spec.duration:
            raw.append((t, "mining_info", ()))
            t += rng.expovariate(spec.miner_rps)

    payload = 0
    for b in range(spec.push_bursts):
        # bursts land simultaneously (identical timestamp) so the
        # runner fires the whole burst concurrently — that simultaneity
        # is what drives the intake's micro-batch coalescing
        at = spec.duration * (b + 1) / (spec.push_bursts + 1)
        for _ in range(spec.burst_size):
            raw.append((at, "push_tx", (("payload", payload),)))
            payload += 1

    for w in range(spec.n_ws):
        rng = _rng(spec, "ws", w)
        cycle = spec.duration / max(spec.ws_churn, 1)
        for c in range(spec.ws_churn):
            conn = f"{w}.{c}"
            start = c * cycle + rng.random() * cycle * 0.2
            raw.append((start, "ws_connect", (("conn", conn),)))
            raw.append((start + cycle * 0.5, "ws_ping", (("conn", conn),)))
            raw.append((start + cycle * 0.8, "ws_close", (("conn", conn),)))

    raw.sort(key=lambda e: (e[0], e[1], e[2]))
    return [LoadEvent(at=round(at, 6), seq=i, kind=kind, params=params)
            for i, (at, kind, params) in enumerate(raw)]


def schedule_fingerprint(events: List[LoadEvent]) -> str:
    """Stable digest of a schedule (determinism tests / provenance)."""
    import hashlib

    h = hashlib.sha256()
    for ev in events:
        h.update(repr((ev.at, ev.seq, ev.kind, ev.params)).encode())
    return h.hexdigest()


def wallet_universe(spec: PopulationSpec) -> Dict[int, int]:
    """How many distinct key indices the harness must back: wallet
    ranks map onto ``min(n_wallets, 48)`` real keypairs (rank modulo),
    keeping fixture setup cheap while preserving the hot/cold split."""
    n_keys = min(spec.n_wallets, 48)
    return {rank: rank % n_keys for rank in range(spec.n_wallets)}
