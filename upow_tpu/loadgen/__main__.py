"""CLI: ``python -m upow_tpu.loadgen`` — run the perf observatory.

Examples::

    python -m upow_tpu.loadgen --smoke --out observatory.json
    python -m upow_tpu.loadgen --progress PROGRESS.jsonl
    python -m upow_tpu.loadgen --smoke --against BENCH_r05.json --report-only
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .population import PopulationSpec


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m upow_tpu.loadgen")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny population (CI-sized)")
    ap.add_argument("--seed", type=lambda s: int(s, 0), default=None,
                    help="population seed (default spec's)")
    ap.add_argument("--bench-seconds", type=float, default=0.4,
                    help="per-kernel measurement window")
    ap.add_argument("--device", action="store_true",
                    help="probe/arm a real accelerator (provenance "
                         "records the failure reason if it degrades)")
    ap.add_argument("--cost", action="store_true",
                    help="record XLA cost_analysis for the jnp search "
                         "kernel (forces a compile)")
    ap.add_argument("--out", default="observatory.json",
                    help="artifact path (default observatory.json)")
    ap.add_argument("--progress", default=None,
                    help="also append a summary line to this JSONL file")
    ap.add_argument("--against", default=None,
                    help="after the run, gate the artifact against this "
                         "baseline")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="gate tolerance override")
    ap.add_argument("--report-only", action="store_true",
                    help="gate reports but never fails the run "
                         "(except --enforce'd metrics)")
    ap.add_argument("--metric-tolerance", action="append", default=[],
                    metavar="NAME=TOL",
                    help="per-metric gate tolerance (passed through)")
    ap.add_argument("--enforce", action="append", default=[],
                    metavar="SUBSTR",
                    help="gate metrics matching SUBSTR even under "
                         "--report-only (passed through)")
    args = ap.parse_args(argv)

    from .coresidency import CoresidencySpec
    from .observatory import append_progress, run_observatory, write_artifact
    from .readpath import ReadpathSpec

    spec = PopulationSpec.smoke() if args.smoke else PopulationSpec()
    rp_spec = ReadpathSpec.smoke() if args.smoke else ReadpathSpec()
    co_spec = CoresidencySpec.smoke() if args.smoke else CoresidencySpec()
    if args.seed is not None:
        spec.seed = args.seed
        rp_spec.seed = args.seed
        co_spec.seed = args.seed

    artifact = run_observatory(spec, bench_seconds=args.bench_seconds,
                               device=args.device, cost=args.cost,
                               readpath_spec=rp_spec,
                               coresidency_spec=co_spec)
    write_artifact(artifact, args.out)
    if args.progress:
        append_progress(artifact, args.progress)

    print(json.dumps({
        "artifact": args.out,
        "events": artifact["slo"]["events"],
        # fleet propagation/node rows carry quantiles only — no req_s
        "endpoints": {ep: {"req_s": row.get("req_s"),
                           "p95_ms": row.get("p95_ms")}
                      for ep, row in artifact["slo"]["endpoints"].items()},
        "provenance": artifact["provenance"],
    }, sort_keys=True))

    if args.against:
        from . import gate

        gate_argv = ["--against", args.against, "--current", args.out]
        if args.tolerance is not None:
            gate_argv += ["--tolerance", str(args.tolerance)]
        if args.report_only:
            gate_argv.append("--report-only")
        for spec_arg in args.metric_tolerance:
            gate_argv += ["--metric-tolerance", spec_arg]
        for substr in args.enforce:
            gate_argv += ["--enforce", substr]
        return gate.main(gate_argv)
    return 0


if __name__ == "__main__":
    sys.exit(main())
