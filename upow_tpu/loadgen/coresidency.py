"""Co-residency scenario: miner + block verify + mempool intake on ONE
device runtime (ISSUE 10 acceptance, bench_suite config 14).

Three subsystem clients hammer a fresh :class:`DeviceRuntime`
concurrently — a saturating miner stream (``source='mine'``, weight 1),
block-verify signature batches (``source='block'``, weight 4) and
mempool-intake batches (``source='mempool'``, weight 2) submitted in
bursts like the intake front produces — while the single drainer
coalesces compatible sig batches across sources and schedules the mix
with weighted fairness.

The differential is built in and decides whether performance numbers
are reported at all: every concurrent verdict slice must be
byte-identical to the serial single-sig host reference AND to a serial
one-dispatch-per-batch pass over the same deterministic batches.  A
divergence zeroes ``coalesce_ratio`` (the headline the gate watches,
direction=higher) and omits the latency/dispatch sections — the same
refuse-to-report convention as readpath/verify_pipeline.

Reported deltas (ISSUE wording: "measurably fewer dispatches, no
verify starvation"):

* ``dispatch_reduction`` — serial sig dispatches / coalesced sig
  dispatches (>1 means the runtime merged cross-source batches).
* ``occupancy`` — aggregate real/padded lanes of the shared
  ``device_runtime`` dispatches vs the serial pass's occupancy.
* ``verify_wait_p99_ms`` — block-source queue wait under the miner
  flood; bounded wait IS the no-starvation claim.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, List

from ..logger import get_logger

log = get_logger("loadgen")

_PAD = 128  # pad_block shared by every sig submission (one dispatch key)


@dataclass
class CoresidencySpec:
    """Fixed-work sizing (wall time follows from the host's speed, so
    the dispatch/occupancy deltas stay deterministic)."""

    seed: int = 0x10C0DE
    n_unique: int = 48        # distinct keypairs/messages in the universe
    invalid_every: int = 5    # corrupted-signature cadence in the mix
    verify_batches: int = 36  # block-verify submissions
    verify_batch: int = 24    # checks per block-verify submission
    intake_batches: int = 54  # mempool submissions
    intake_batch: int = 6     # checks per mempool submission
    burst: int = 6            # submissions in flight per source client
    miner_chunk: int = 1500   # hashlib nonces per miner dispatch

    @classmethod
    def smoke(cls) -> "CoresidencySpec":
        return cls(n_unique=24, verify_batches=12, intake_batches=18,
                   miner_chunk=600)

    def to_dict(self) -> dict:
        return asdict(self)


def _host_reference(checks) -> List[bool]:
    """Serial single-sig host verdicts — the semantics every batched or
    coalesced path must reproduce bit for bit."""
    from ..verify import txverify

    return [bool(txverify._host_verify_digest(c[0], c[2], c[3])
                 or txverify._host_verify_digest(c[1], c[2], c[3]))
            for c in checks]


def _build_batches(spec: CoresidencySpec):
    """Deterministic (source, checks) work lists for both passes."""
    from ..benchutil import pipeline_verify_fixture

    total = (spec.verify_batches * spec.verify_batch
             + spec.intake_batches * spec.intake_batch)
    checks = pipeline_verify_fixture(total, n_unique=spec.n_unique,
                                     invalid_every=spec.invalid_every,
                                     rng_base=spec.seed & 0xFFFF)
    batches = []
    cursor = 0
    for _ in range(spec.verify_batches):
        batches.append(("block", checks[cursor:cursor + spec.verify_batch]))
        cursor += spec.verify_batch
    for _ in range(spec.intake_batches):
        batches.append(("mempool", checks[cursor:cursor + spec.intake_batch]))
        cursor += spec.intake_batch
    return batches


def _miner_work(chunk: int, base: int):
    """One miner dispatch: a hashlib stride over ``chunk`` nonces —
    the reference miner's hot loop shape, cheap and GIL-releasing
    enough to model a saturating device stream on the drainer."""
    prefix = b"coresidency-miner" + base.to_bytes(8, "big")
    h = 0
    for n in range(base, base + chunk):
        h ^= hashlib.sha256(prefix + n.to_bytes(4, "little")).digest()[0]
    return h


def _p99_ms(waits: List[float]) -> float:
    if not waits:
        return 0.0
    ordered = sorted(waits)
    return round(ordered[min(len(ordered) - 1,
                             int(len(ordered) * 0.99))] * 1000.0, 3)


def run_coresidency(spec: CoresidencySpec = None) -> dict:
    """Serial reference pass, then the concurrent co-residency pass on a
    fresh runtime; return the scenario artifact."""
    from ..device.runtime import DeviceRuntime
    from ..telemetry import metrics
    from ..verify import txverify

    spec = spec or CoresidencySpec()
    batches = _build_batches(spec)
    expected = {i: _host_reference(c) for i, (_, c) in enumerate(batches)}

    diff = {"ok": True, "checks": 0, "mismatches": 0}

    # --- serial pass: one dispatch per batch, the pre-runtime shape ----
    txverify.clear_sig_verdicts()
    t0 = time.perf_counter()
    serial_lanes = 0
    for i, (_, checks) in enumerate(batches):
        got = txverify.run_sig_checks(checks, backend="host",
                                      pad_block=_PAD, use_cache=False)
        serial_lanes += len(checks)
        diff["checks"] += 1
        if got != expected[i]:
            diff["mismatches"] += 1
            diff["ok"] = False
    serial_seconds = time.perf_counter() - t0
    serial_dispatches = len(batches)
    serial_padded = serial_dispatches * _PAD
    serial_occupancy = round(serial_lanes / serial_padded, 4)

    # --- concurrent pass: miner + verify + intake on one runtime ------
    txverify.clear_sig_verdicts()
    rt = DeviceRuntime()
    counters0 = metrics.counters()
    real0 = counters0.get("kernel.device_runtime.lanes_real", 0)
    padded0 = counters0.get("kernel.device_runtime.lanes_padded", 0)
    sig_done = threading.Event()
    miner_chunks = [0]
    errors: List[str] = []

    def sig_client(source: str):
        mine_batches = [(i, c) for i, (s, c) in enumerate(batches)
                        if s == source]
        cursor = 0
        try:
            while cursor < len(mine_batches):
                wave = mine_batches[cursor:cursor + spec.burst]
                futs = [(i, rt.submit_sig_checks(
                    c, backend="host", pad_block=_PAD, source=source))
                    for i, c in wave]
                for i, fut in futs:
                    got = fut.result(timeout=120.0)
                    diff["checks"] += 1
                    if got != expected[i]:
                        diff["mismatches"] += 1
                        diff["ok"] = False
                cursor += spec.burst
        except Exception as e:
            log.warning("coresidency %s client failed: %r", source, e)
            errors.append("%s client: %r" % (source, e))

    def miner_client():
        base = 0
        try:
            while not sig_done.is_set():
                fut = rt.submit_call(
                    lambda b=base: _miner_work(spec.miner_chunk, b),
                    kernel="pow_chunk", source="mine")
                fut.result(timeout=120.0)
                miner_chunks[0] += 1
                base += spec.miner_chunk
        except Exception as e:
            log.warning("coresidency miner client failed: %r", e)
            errors.append("miner client: %r" % (e,))

    t0 = time.perf_counter()
    miner = threading.Thread(target=miner_client, daemon=True)
    clients = [threading.Thread(target=sig_client, args=(s,), daemon=True)
               for s in ("block", "mempool")]
    miner.start()
    for c in clients:
        c.start()
    for c in clients:
        c.join(timeout=300.0)
    sig_done.set()
    miner.join(timeout=300.0)
    concurrent_seconds = time.perf_counter() - t0

    stats = rt.stats()
    counters1 = metrics.counters()
    rt.close()
    if errors:
        diff["ok"] = False
        diff["errors"] = errors

    per_source = stats["per_source"]
    mine_n = per_source.get("mine", 0)
    sig_submissions = per_source.get("block", 0) \
        + per_source.get("mempool", 0)
    sig_dispatches = max(1, stats["dispatches"] - mine_n)
    # each miner call records one real/padded lane pair; subtract them
    # to isolate the shared sig dispatches' occupancy
    lanes_real = counters1.get("kernel.device_runtime.lanes_real", 0) \
        - real0 - mine_n
    lanes_padded = counters1.get("kernel.device_runtime.lanes_padded", 0) \
        - padded0 - mine_n

    result = {
        "kind": "coresidency",
        "spec": spec.to_dict(),
        "differential": diff,
        "serial": {
            "dispatches": serial_dispatches,
            "occupancy": serial_occupancy,
            "seconds": round(serial_seconds, 3),
        },
    }
    if not diff["ok"]:
        log.warning("coresidency differential FAILED (%d/%d probes) — "
                    "refusing to report dispatch deltas",
                    diff["mismatches"], diff["checks"])
        result["coalesce_ratio"] = 0.0
        return result

    waits = stats["queue_waits"]
    result["concurrent"] = {
        "seconds": round(concurrent_seconds, 3),
        "submissions": stats["submissions"],
        "dispatches": stats["dispatches"],
        "per_source": per_source,
        "miner_chunks": miner_chunks[0],
        "sig_submissions": sig_submissions,
        "sig_dispatches": sig_dispatches,
        "occupancy": round(lanes_real / lanes_padded, 4)
        if lanes_padded > 0 else None,
        "verify_wait_p99_ms": _p99_ms(waits.get("block", [])),
        "intake_wait_p99_ms": _p99_ms(waits.get("mempool", [])),
        "mine_wait_p99_ms": _p99_ms(waits.get("mine", [])),
    }
    result["coalesce_ratio"] = round(sig_submissions / sig_dispatches, 3)
    result["dispatch_reduction"] = round(
        serial_dispatches / sig_dispatches, 3)
    return result
