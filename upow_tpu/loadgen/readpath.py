"""Read-path cache scenario: Zipfian wallets under block cadence.

Boots a real node over the funded fixture and replays the SAME
deterministic request schedule twice — once with the
``X-Upow-Cache-Bypass`` header on every request (every response
computed fresh from state) and once through the hot-state cache —
while mining blocks at a fixed cadence so each pass pays the same
invalidation churn.  The headline is the p99 speedup of the cached
pass over the bypassed one.

The differential is built in and runs FIRST: at every chain-mutation
stage (initial, post-block, forced reorg via ``remove_blocks``,
re-accept) each sampled endpoint is fetched twice through the cache
and once bypassed, and all three bodies must be byte-identical.  Any
mismatch means the cache returned something state would not have — the
scenario then refuses to report performance: latency sections are
omitted and ``speedup_p99`` is zeroed, the same divergence-trips-the-
gate convention as ``verify_pipeline_speedup``.
"""

from __future__ import annotations

import random
import time
from dataclasses import asdict, dataclass
from typing import Dict, List, Tuple

from ..logger import get_logger
from .runner import summarize_latencies

log = get_logger("loadgen")

_BYPASS_HEADER = "X-Upow-Cache-Bypass"

# (endpoint tag, path, params) — tag groups latencies per endpoint
Request = Tuple[str, str, Dict[str, str]]


@dataclass
class ReadpathSpec:
    """Sizing knobs.  ``block_every`` sets the invalidation cadence:
    every window of that many requests starts with a fresh generation,
    so the first touch of each distinct key after the bump is a miss —
    keep the window two orders of magnitude above the distinct-key
    count or the cached p99 lands on recompute latency, not hits."""

    seed: int = 0xC0FFEE
    n_wallets: int = 12       # address universe; rank 0 = funded hot wallet
    zipf_s: float = 1.2
    n_requests: int = 3000    # per pass
    block_every: int = 1500   # mine (→ invalidate) every N requests
    n_fan: int = 12           # fixture fanout: n_fan * n_per leaf UTXOs
    n_per: int = 48           # (the hot wallet is BIG — that's the point)
    history_limit: int = 25   # per-row get_nice_transaction queries
    blocks_limit: int = 60    # tx-detailed block pages

    @classmethod
    def smoke(cls) -> "ReadpathSpec":
        # same per-request weight as the default (so the smoke artifact
        # gates cleanly against a full-run baseline); just fewer of them
        return cls(n_wallets=6, n_requests=1200, block_every=600)

    def to_dict(self) -> dict:
        return asdict(self)


def build_readpath_schedule(spec: ReadpathSpec, addresses: List[str],
                            tx_hash: str) -> List[Request]:
    """Deterministic request mix: Zipf-ranked wallet reads (the heavy
    funded account is the hot spot), miner template polling, and the
    public chain/browser queries the cache fronts."""
    rng = random.Random(spec.seed)
    ranks = list(range(len(addresses)))
    weights = [1.0 / (r + 1) ** spec.zipf_s for r in ranks]

    def wallet() -> str:
        return addresses[rng.choices(ranks, weights)[0]]

    events: List[Request] = []
    for _ in range(spec.n_requests):
        roll = rng.random()
        if roll < 0.40:
            events.append(("address_info", "/get_address_info",
                           {"address": wallet(), "show_pending": "true",
                            "verify": "true"}))
        elif roll < 0.60:
            events.append(("history", "/get_address_transactions",
                           {"address": wallet(),
                            "limit": str(spec.history_limit)}))
        elif roll < 0.75:
            events.append(("mining_info", "/get_mining_info", {}))
        elif roll < 0.85:
            events.append(("blocks_details", "/get_blocks_details",
                           {"offset": "0",
                            "limit": str(spec.blocks_limit)}))
        elif roll < 0.93:
            events.append(("supply", "/get_supply_info", {}))
        else:
            events.append(("tx", "/get_transaction", {"tx_hash": tx_hash}))
    return events


def _differential_requests(hot_addr: str, cold_addr: str,
                           tx_hash: str) -> List[Tuple[str, Dict[str, str]]]:
    """One probe per cached entry class (plus variants that share a
    class but must not share a key)."""
    return [
        ("/get_address_info", {"address": hot_addr, "show_pending": "true",
                               "verify": "true"}),
        ("/get_address_info", {"address": cold_addr}),
        ("/get_address_transactions", {"address": hot_addr, "limit": "8"}),
        ("/get_pending_transactions", {}),
        ("/get_supply_info", {}),
        ("/get_blocks", {"offset": "0", "limit": "10"}),
        ("/get_blocks_details", {"offset": "0", "limit": "5"}),
        ("/get_block", {"block": "2", "full_transactions": "true"}),
        ("/get_block", {"block": "2"}),
        ("/get_block_details", {"block": "2"}),
        ("/get_transaction", {"tx_hash": tx_hash}),
        ("/get_validators_info", {}),
        ("/get_delegates_info", {}),
    ]


async def _fetch(client, path: str, params: Dict[str, str],
                 bypass: bool) -> Tuple[int, bytes, float]:
    headers = {_BYPASS_HEADER: "1"} if bypass else {}
    t0 = time.perf_counter()
    resp = await client.get(path, params=params, headers=headers)
    body = await resp.read()
    return resp.status, body, time.perf_counter() - t0


async def _diff_stage(client, reqs, stage: str, diff: dict) -> None:
    """cached-populate, cached-hit, bypass — all three byte-identical
    or the stage records a mismatch (and the run refuses to report)."""
    mismatches = []
    for path, params in reqs:
        s1, b1, _ = await _fetch(client, path, params, bypass=False)
        s2, b2, _ = await _fetch(client, path, params, bypass=False)
        s3, b3, _ = await _fetch(client, path, params, bypass=True)
        diff["checks"] += 1
        if not (s1 == s2 == s3 and b1 == b2 == b3):
            diff["mismatches"] += 1
            diff["ok"] = False
            mismatches.append({
                "path": path, "params": params,
                "status": [s1, s2, s3],
                "cached_first": b1[:160].decode("utf-8", "replace"),
                "cached_hit": b2[:160].decode("utf-8", "replace"),
                "bypass": b3[:160].decode("utf-8", "replace")})
    diff["stages"].append({"stage": stage, "probes": len(reqs),
                           "mismatches": mismatches})


async def _run_pass(client, schedule: List[Request], mine_block,
                    block_every: int, bypass: bool) -> Dict[str, List[float]]:
    lat: Dict[str, List[float]] = {}
    for i, (tag, path, params) in enumerate(schedule):
        if block_every and i and i % block_every == 0:
            await mine_block([])
        status, _, dt = await _fetch(client, path, params, bypass)
        if status != 200:
            raise RuntimeError(
                f"readpath: {path} answered {status} (bypass={bypass})")
        lat.setdefault(tag, []).append(dt)
    return lat


async def run_readpath(spec: ReadpathSpec = None) -> dict:
    """Run differential + both passes; return the scenario artifact."""
    from aiohttp.test_utils import TestClient, TestServer

    from ..benchutil import chain_with_utxo_fanout
    from ..config import Config
    from ..core import clock, curve, point_to_string
    from ..node.app import Node

    spec = spec or ReadpathSpec()
    state, fix_manager, _d, _pub, addr, mids, mine_block = \
        await chain_with_utxo_fanout(spec.n_fan, spec.n_per,
                                     spec.seed & 0xFFFF)
    addresses = [addr]
    for i in range(1, spec.n_wallets):
        _, pub_i = curve.keygen(rng=(spec.seed << 8) ^ (0xCA5E + i))
        addresses.append(point_to_string(pub_i))
    tx_hash = mids[0].hash()

    cfg = Config()
    cfg.node.db_path = ""
    cfg.node.seed_url = ""
    cfg.node.peers_file = ""
    cfg.node.ip_config_file = ""
    cfg.log.path = ""
    cfg.log.console = False
    # sole writer: the hooks, not the revalidation backstop, must keep
    # the cache honest — exactly what the differential interrogates
    cfg.cache.revalidate_interval = -1.0
    node = Node(cfg, state=state)
    if node.hotcache.enabled:
        # blocks here land through the FIXTURE's manager, not the
        # node's, so point its post-commit hook at the same bump (the
        # reorg path is already covered by state.on_blocks_removed)
        fix_manager.on_state_committed = node.hotcache.bump
    server = TestServer(node.app)
    await server.start_server()
    client = TestClient(server)
    node.started = True
    node.rate_limiter.enabled = False
    try:
        diff = {"ok": True, "checks": 0, "mismatches": 0, "stages": []}
        reqs = _differential_requests(addr, addresses[-1], tx_hash)
        await _diff_stage(client, reqs, "initial", diff)
        await mine_block([])
        await _diff_stage(client, reqs, "post_block", diff)
        last = await state.get_last_block()
        await state.remove_blocks(last["id"])  # forced reorg of the tip
        await _diff_stage(client, reqs, "post_reorg", diff)
        await mine_block([])
        await _diff_stage(client, reqs, "post_reaccept", diff)

        schedule = build_readpath_schedule(spec, addresses, tx_hash)
        bypass_lat = await _run_pass(client, schedule, mine_block,
                                     spec.block_every, bypass=True)
        stats0 = node.hotcache.stats()
        cached_lat = await _run_pass(client, schedule, mine_block,
                                     spec.block_every, bypass=False)
        stats1 = node.hotcache.stats()
    finally:
        await client.close()
        await server.close()
        await node.close()
        clock.reset()

    hits = stats1["hits"] - stats0["hits"]
    misses = stats1["misses"] - stats0["misses"]
    result = {
        "kind": "readpath",
        "spec": spec.to_dict(),
        "differential": diff,
        "cache": stats1,
        "cached_pass": {
            "hits": hits, "misses": misses,
            "hit_ratio": round(hits / (hits + misses), 4)
            if hits + misses else None},
    }
    if not diff["ok"]:
        log.warning("readpath differential FAILED (%d/%d probes) — "
                    "refusing to report latencies",
                    diff["mismatches"], diff["checks"])
        result["speedup_p99"] = 0.0
        return result

    flat_bypass = [v for vals in bypass_lat.values() for v in vals]
    flat_cached = [v for vals in cached_lat.values() for v in vals]
    result["bypass"] = summarize_latencies(flat_bypass)
    result["cached"] = summarize_latencies(flat_cached)
    result["per_endpoint"] = {
        tag: {"bypass": summarize_latencies(bypass_lat[tag]),
              "cached": summarize_latencies(cached_lat[tag])}
        for tag in sorted(bypass_lat) if tag in cached_lat}
    cached_p99 = result["cached"]["p99_ms"]
    result["speedup_p99"] = round(
        result["bypass"]["p99_ms"] / cached_p99, 2) if cached_p99 else None
    return result
