"""Schedule execution + per-endpoint SLO summary.

The runner replays a :func:`..population.build_schedule` event list
against an *executor* — any ``async callable(LoadEvent) -> ExecResult``.
Events sharing a timestamp (push bursts) run concurrently under one
``asyncio.gather``; distinct timestamps run in order.  There is no
wall-clock pacing: the run is closed-loop, so throughput numbers mean
"as fast as the target serves", not "as fast as we asked".

Two executors exist:

* :class:`MockBackend` — latency derived purely from (seed, event);
  same seed → byte-identical summary regardless of scheduling order.
  This is what the determinism test pins, and it feeds the same
  ``telemetry.slo`` histograms the real middleware does so exposition
  tests don't need a node.
* ``harness.HttpExecutor`` — the real in-process node (aiohttp).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..logger import get_logger
from ..telemetry import slo
from .population import LoadEvent, PopulationSpec, build_schedule

log = get_logger("loadgen")


@dataclass(frozen=True)
class ExecResult:
    endpoint: str
    status: int
    ok: bool
    latency: float            # seconds


async def run_schedule(events: Sequence[LoadEvent],
                       executor) -> List[Optional[ExecResult]]:
    """Execute every event; a failed executor call becomes a synthetic
    status-599 result rather than aborting the run."""

    async def one(ev: LoadEvent) -> ExecResult:
        try:
            return await executor(ev)
        except Exception as e:  # keep the population running; count it
            log.debug("executor failed on %s#%d: %s", ev.kind, ev.seq, e)
            return ExecResult(endpoint=ev.endpoint, status=599, ok=False,
                              latency=0.0)

    results: List[Optional[ExecResult]] = []
    i = 0
    while i < len(events):
        j = i
        while j < len(events) and events[j].at == events[i].at:
            j += 1
        wave = events[i:j]
        if len(wave) == 1:
            results.append(await one(wave[0]))
        else:
            results.extend(await asyncio.gather(*(one(ev) for ev in wave)))
        i = j
    return results


def _exact_quantile(sorted_lat: List[float], q: float) -> float:
    """Nearest-rank quantile over the runner's own measurements (exact,
    unlike the bucket-interpolated server-side estimate)."""
    idx = min(len(sorted_lat) - 1, max(0, int(q * len(sorted_lat))))
    return sorted_lat[idx]


def summarize_latencies(values: Sequence[float]) -> dict:
    """Exact quantile summary over raw latency samples (seconds) — the
    per-endpoint shape above, minus req/s; the swarm harness uses it
    for per-node client-side SLO summaries."""
    ordered = sorted(values)
    return {
        "requests": len(ordered),
        "p50_ms": round(_exact_quantile(ordered, 0.50) * 1000, 4),
        "p95_ms": round(_exact_quantile(ordered, 0.95) * 1000, 4),
        "p99_ms": round(_exact_quantile(ordered, 0.99) * 1000, 4),
    }


def summarize(events: Sequence[LoadEvent],
              results: Sequence[Optional[ExecResult]],
              elapsed: float) -> dict:
    """Client-side per-endpoint req/s + exact p50/p95/p99 (ms)."""
    lat: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    for res in results:
        if res is None:
            continue
        lat.setdefault(res.endpoint, []).append(res.latency)
        if not res.ok:
            errors[res.endpoint] = errors.get(res.endpoint, 0) + 1
    endpoints = {}
    for ep, values in sorted(lat.items()):
        values.sort()
        endpoints[ep.strip("/") or "root"] = {
            "requests": len(values),
            "errors": errors.get(ep, 0),
            "req_s": round(len(values) / elapsed, 3) if elapsed else None,
            "p50_ms": round(_exact_quantile(values, 0.50) * 1000, 4),
            "p95_ms": round(_exact_quantile(values, 0.95) * 1000, 4),
            "p99_ms": round(_exact_quantile(values, 0.99) * 1000, 4),
        }
    return {
        "events": len(events),
        "elapsed_s": round(elapsed, 4),
        "endpoints": endpoints,
    }


class MockBackend:
    """Deterministic synthetic target: latency is a pure function of
    (seed, event seq/kind), so neither asyncio scheduling nor host
    speed can perturb the summary."""

    BASE_LATENCY = {
        "balance": 0.004, "utxo": 0.006, "history": 0.008,
        "mining_info": 0.002, "push_tx": 0.012,
        "ws_connect": 0.003, "ws_ping": 0.001, "ws_close": 0.001,
    }

    def __init__(self, seed: int, record_slo: bool = True):
        self.seed = seed
        self.record_slo = record_slo

    def _latency(self, ev: LoadEvent) -> float:
        digest = hashlib.sha256(
            f"{self.seed}:{ev.seq}:{ev.kind}".encode()).digest()
        jitter = int.from_bytes(digest[:8], "big") / 2.0 ** 64
        return self.BASE_LATENCY[ev.kind] * (0.5 + jitter)

    async def __call__(self, ev: LoadEvent) -> ExecResult:
        latency = self._latency(ev)
        if self.record_slo and ev.endpoint.startswith("/"):
            # same series the node middleware feeds, so exposition
            # tests exercise the slo histograms without booting a node
            slo.observe_request(ev.endpoint, latency, 200)
        return ExecResult(endpoint=ev.endpoint, status=200, ok=True,
                          latency=latency)


def run_mock(spec: PopulationSpec, record_slo: bool = True) -> dict:
    """Build + execute the schedule against the mock backend.  The
    summary's elapsed is the spec's virtual duration (deterministic);
    wall time is reported separately for the curious."""
    events = build_schedule(spec)
    backend = MockBackend(spec.seed, record_slo=record_slo)
    t0 = time.perf_counter()
    results = asyncio.run(run_schedule(events, backend))
    wall = time.perf_counter() - t0
    summary = summarize(events, results, elapsed=spec.duration)
    summary["wall_s"] = round(wall, 4)
    summary["backend"] = "mock"
    return summary
