"""Deterministic wallet-population load generator (ROADMAP item 4).

Drives an in-process node with a seeded, realistic request mix —
Zipfian hot-account balance/UTXO reads, miner ``get_mining_info``
polling, push_tx bursts through the coalescing intake, and WebSocket
subscriber churn — and records per-endpoint req/s plus p50/p95/p99
latency, both client-side (exact quantiles in the run summary) and
server-side (``slo.http.*`` histograms on ``/metrics``).

Layout (import-light on purpose: :mod:`.gate` must run with stdlib
only, and ``python -m upow_tpu.loadgen.gate`` imports this package):

* :mod:`.population` — seeded schedule builder (stdlib only).
* :mod:`.runner`     — schedule execution + summary (stdlib + asyncio);
  includes the deterministic mock backend the tests pin.
* :mod:`.harness`    — the real in-process node target (aiohttp).
* :mod:`.observatory` — merged SLO + kernel-bench artifact with
  capture provenance; ``python -m upow_tpu.loadgen`` entry point.
* :mod:`.gate`       — stdlib regression checker
  (``python -m upow_tpu.loadgen.gate --against BENCH_r05.json``).
"""

from .population import LoadEvent, PopulationSpec, build_schedule  # noqa: F401

__all__ = ["LoadEvent", "PopulationSpec", "build_schedule"]
