"""In-process node target for the load generator.

Boots a real :class:`~upow_tpu.node.app.Node` over an in-memory chain
pre-funded through :func:`~upow_tpu.benchutil.chain_with_utxo_fanout`
(so push_tx bursts carry *valid, accepted* spends through the
coalescing intake, not just parse errors) and serves it via aiohttp's
TestServer — the same harness idiom as bench_suite configs 8/10 and
the telemetry selfcheck.

The executor translates abstract schedule events into wire requests:

* ``balance`` / ``utxo`` / ``history`` — address reads for the wallet
  universe (rank 0 = the funded hot account, the rest fresh keypairs).
* ``mining_info`` — template polling (generation-keyed cache path).
* ``push_tx`` — POST through the mempool intake; payloads are
  pre-signed 1-in-1-out leaf spends, reused modulo the pool when a
  schedule asks for more than the fixture funded (duplicates exercise
  the dedup/conflict path, still a served request).
* ``ws_connect`` / ``ws_ping`` / ``ws_close`` — subscriber churn
  against the hub, latency = time to the acknowledging frame.
"""

from __future__ import annotations

import asyncio
import tempfile
import time
from typing import Dict, List

from ..logger import get_logger
from .population import LoadEvent, PopulationSpec, build_schedule
from .runner import ExecResult, run_schedule, summarize

log = get_logger("loadgen")

_WS_ACK_TIMEOUT = 5.0


class HttpExecutor:
    """async callable(LoadEvent) -> ExecResult against a TestClient."""

    def __init__(self, client, addresses: List[str],
                 payloads: List[str]):
        self.client = client
        self.addresses = addresses
        self.payloads = payloads
        self._ws: Dict[str, object] = {}

    async def _http(self, ev: LoadEvent) -> ExecResult:
        t0 = time.perf_counter()
        if ev.kind == "push_tx":
            payload = self.payloads[ev.param("payload", 0)
                                    % len(self.payloads)]
            resp = await self.client.post("/push_tx",
                                          json={"tx_hex": payload})
        elif ev.kind == "mining_info":
            resp = await self.client.get("/get_mining_info")
        else:
            addr = self.addresses[ev.param("wallet", 0)
                                  % len(self.addresses)]
            if ev.kind == "history":
                resp = await self.client.get(
                    "/get_address_transactions",
                    params={"address": addr, "limit": "5"})
            else:
                params = {"address": addr}
                if ev.kind == "utxo":
                    params["show_pending"] = "true"
                resp = await self.client.get("/get_address_info",
                                             params=params)
        body = await resp.json()
        latency = time.perf_counter() - t0
        # push_tx duplicates/conflicts answer ok=False on a 200 — a
        # served request, not an executor error
        ok = resp.status < 500 and (ev.kind == "push_tx"
                                    or bool(body.get("ok", True)))
        return ExecResult(endpoint=ev.endpoint, status=resp.status,
                          ok=ok, latency=latency)

    async def _ws_event(self, ev: LoadEvent) -> ExecResult:
        conn_id = ev.param("conn")
        t0 = time.perf_counter()
        ok = True
        if ev.kind == "ws_connect":
            ws = await self.client.ws_connect("/ws")
            self._ws[conn_id] = ws
            # connection_established frame, then the subscribe ack
            await asyncio.wait_for(ws.receive_json(),
                                   timeout=_WS_ACK_TIMEOUT)
            await ws.send_json({"type": "subscribe_block"})
            ack = await asyncio.wait_for(ws.receive_json(),
                                         timeout=_WS_ACK_TIMEOUT)
            ok = ack.get("type") != "error"
        elif ev.kind == "ws_ping":
            ws = self._ws.get(conn_id)
            if ws is None or ws.closed:
                ok = False
            else:
                await ws.send_json({"type": "ping"})
                pong = await asyncio.wait_for(ws.receive_json(),
                                              timeout=_WS_ACK_TIMEOUT)
                ok = pong.get("type") == "pong"
        else:  # ws_close
            ws = self._ws.pop(conn_id, None)
            if ws is not None and not ws.closed:
                await ws.close()
        return ExecResult(endpoint="ws", status=200 if ok else 599,
                          ok=ok, latency=time.perf_counter() - t0)

    async def __call__(self, ev: LoadEvent) -> ExecResult:
        if ev.kind.startswith("ws_"):
            return await self._ws_event(ev)
        return await self._http(ev)

    async def close(self) -> None:
        for ws in list(self._ws.values()):
            try:
                if not ws.closed:
                    await ws.close()
            except Exception as e:
                log.debug("ws cleanup close failed: %s", e)
        self._ws.clear()


def _wallet_addresses(spec: PopulationSpec, funded_addr: str) -> List[str]:
    """Rank-indexed address universe: the funded account is the Zipf
    hot spot; the rest are fresh (empty) keypairs — real addresses, so
    reads exercise the same state queries either way."""
    from ..core import curve, point_to_string

    n_keys = min(spec.n_wallets, 48)
    addresses = [funded_addr]
    for i in range(1, n_keys):
        _, pub = curve.keygen(rng=(spec.seed << 8) ^ (0xA0D0 + i))
        addresses.append(point_to_string(pub))
    return addresses


async def run_against_node(spec: PopulationSpec) -> dict:
    """Build the funded fixture, boot the node in-process, drive the
    schedule, return the merged summary (client-side quantiles + the
    node's own slo/ws/mempool counters)."""
    from aiohttp.test_utils import TestClient, TestServer

    from ..benchutil import chain_with_utxo_fanout, leaf_spends
    from ..config import Config
    from ..core import clock
    from ..node.app import Node

    events = build_schedule(spec)
    needed = spec.push_bursts * spec.burst_size
    n_per = 24
    n_fan = max(2, -(-needed // n_per))  # ceil division

    state, _manager, d, pub, addr, mids, _mine = \
        await chain_with_utxo_fanout(n_fan, n_per, spec.seed & 0xFFFF)
    payloads = [t.hex() for t in leaf_spends(mids, addr, d, pub)]
    addresses = _wallet_addresses(spec, addr)

    cfg = Config()
    with tempfile.TemporaryDirectory() as tmp:
        cfg.node.db_path = ""
        cfg.node.seed_url = ""
        cfg.node.peers_file = f"{tmp}/nodes.json"
        cfg.node.ip_config_file = ""
        cfg.log.path = ""
        cfg.log.console = False
        node = Node(cfg, state=state)
        server = TestServer(node.app)
        await server.start_server()
        client = TestClient(server)
        node.started = True
        node.rate_limiter.enabled = False  # measuring us, not limits
        executor = HttpExecutor(client, addresses, payloads)
        try:
            t0 = time.perf_counter()
            results = await run_schedule(events, executor)
            elapsed = time.perf_counter() - t0
        finally:
            await executor.close()
            await client.close()
            await server.close()
            await node.close()
            clock.reset()

    summary = summarize(events, results, elapsed)
    summary["backend"] = "node-inprocess"
    summary["population"] = spec.to_dict()
    if node.ws_hub is not None:
        summary["ws_hub"] = node.ws_hub.get_stats()
    from ..telemetry import slo

    summary["server_slo"] = slo.summary()
    return summary
