"""The five BASELINE.json measurement configs plus the chain-level
configs, one JSON line each.

    python bench_suite.py [--configs 1,...,9] [--seconds N]

1. miner single-block sha256 at difficulty 1 (CPU reference loop)
2. fractional difficulty 6.3 mine (charset-restricted prefix match)
3. 8k-tx block P-256 ECDSA batch-verify
4. full-chain replay validate (rebuild_utxos + fingerprint oracle)
5. mesh-sharded nonce search at difficulty 8 (all visible devices)
6. full 8,160-tx block accept through BlockManager, cold (signatures
   never seen) and warm (every tx intake-verified first — the gossip
   profile, where the verdict cache removes signature work)
7. host-vs-device batched txid hashing crossover (sync pages)
8. push_tx intake over real localhost HTTP (per-tx gossip ingest)
9. end-to-end HTTP chain sync, wire to state (cold catch-up)
10. coalesced push_tx waves through the micro-batching intake
11. perf observatory: wallet-population loadgen SLO + kernel artifact
12. verify_pipeline: pipelined verify engine (coalesced front + verdict
    cache, steady state) vs serial per-tx host dispatch + differential
13. readpath: block-anchored hot-state read cache vs the bypassed SQL
    path under block cadence, byte-identity differential built in
14. coresidency: miner + block verify + mempool intake sharing ONE
    device runtime — cross-source coalescing and fairness deltas,
    byte-identity differential built in
15. accept_resident: end-to-end 8k-tx block accept, SQL membership
    path vs the HBM-resident fused accept (device probe + digest prep
    in one dispatch), byte-identity differential incl. forced reorg +
    re-accept built in
16. mining_mesh: resident mesh-sharded nonce search (one compiled SPMD
    program, job fields as runtime data) vs the serial single-device
    path — bit-identity differential over seeded jobs built in, plus
    per-shard-count hashrate rows

``bench.py`` stays the driver-facing single-line headline (sha256
search + the verify sub-metric); this suite is the full scoreboard.
Each line mirrors bench.py's shape:
``{"metric", "value", "unit", "vs_baseline"}``.
"""

import argparse
import asyncio
import hashlib
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


_PLATFORM = None


def _platform() -> str:
    """Probe the backend once (shared logic: upow_tpu.benchutil) —
    'hung' skips the device-bound configs rather than wedging the run."""
    global _PLATFORM
    if _PLATFORM is None:
        from upow_tpu.benchutil import probe_platform

        _PLATFORM = probe_platform(90.0) or "hung"
    return _PLATFORM


def _emit(metric, value, unit, baseline, direction=None):
    line = {
        "metric": metric, "value": round(value, 3), "unit": unit,
        "vs_baseline": round(value / baseline, 1) if baseline else None,
    }
    if direction:
        # explicit gate direction (upow_tpu.loadgen.gate honors it over
        # its name inference — "speedup_p99" would otherwise read as a
        # latency)
        line["direction"] = direction
    print(json.dumps(line), flush=True)


def _python_loop_mhs(prefix: bytes, seconds: float = 1.0) -> float:
    from upow_tpu.benchutil import python_loop_mhs

    return python_loop_mhs(prefix, seconds)


def _job(difficulty: str, rng: int = 0xBE7C):
    from upow_tpu.core import curve, point_to_string
    from upow_tpu.core.header import BlockHeader
    from upow_tpu.core.merkle import merkle_root
    from upow_tpu.mine.engine import MiningJob

    _, pub = curve.keygen(rng=rng)
    prev = hashlib.sha256(rng.to_bytes(4, "big")).hexdigest()
    header = BlockHeader(
        previous_hash=prev, address=point_to_string(pub),
        merkle_root=merkle_root([]), timestamp=1_753_791_000,
        difficulty_x10=int(float(difficulty) * 10), nonce=0)
    return MiningJob(header.prefix_bytes(), prev, difficulty)


def config1_cpu_reference(seconds: float):
    """Reference-shaped hashlib loop (miner.py:83-98) at difficulty 1:
    verifies a block is found, reports the sustained loop rate (a
    difficulty-1 hit lands in ~16 hashes, far too few to time)."""
    from upow_tpu.mine.engine import mine

    job = _job("1.0")
    result = mine(job, "python", batch=1 << 14, ttl=seconds * 10)
    assert result.nonce is not None and job.check(result.nonce)
    _emit("mine_d1_python_cpu", _python_loop_mhs(job.prefix, seconds),
          "MH/s", None)


def config2_fractional(seconds: float, backend: str):
    """Difficulty 6.3: the fractional charset restricts the 7th nibble."""
    from upow_tpu.mine.engine import mine

    job = _job("6.3")
    batch = 1 << 26 if backend == "pallas" else 1 << 20
    result = mine(job, backend, batch=batch, ttl=seconds * 6)
    base = _python_loop_mhs(job.prefix)
    _emit(f"mine_d6.3_{backend}_{_platform()}",
          result.hashrate / 1e6, "MH/s", base)
    if result.nonce is not None:
        assert job.check(result.nonce)


def config3_batch_verify(seconds: float):
    """8k-signature block verify (the reference's per-input fastecdsa
    loop, transaction_input.py:100-109, measures ~2-6k/s/core)."""
    from upow_tpu.benchutil import python_verify_rate, verify_fixture
    from upow_tpu.crypto import p256

    digests, sigs, pubs, msgs = verify_fixture(8192, n_unique=256)

    # host baseline: pure-python ECDSA verify, short sample
    base_rate = python_verify_rate(msgs, sigs, pubs)

    v = p256.verify_batch_prehashed(digests, sigs, pubs, pad_block=8192)
    assert all(v)
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < seconds:
        v = p256.verify_batch_prehashed(digests, sigs, pubs, pad_block=8192)
        reps += 1
    rate = reps * 8192 / (time.perf_counter() - t0)
    _emit(f"verify_8k_batch_{_platform()}", rate, "sigs/s", base_rate)

    # kernel-only split (host prep + transfer excluded): how much of the
    # end-to-end gap is the device program vs the host pipeline
    import jax

    import upow_tpu.crypto.p256 as P

    captured = {}
    orig_pallas, orig_jnp = P._prep_and_verify_pallas, P._prep_and_verify_jnp
    orig_jac = P._prep_and_verify_pallas_jac

    def cap_pallas(*a, **kw):
        captured["call"] = lambda: orig_pallas(*a, **kw)
        return orig_pallas(*a, **kw)

    def cap_jac(*a, **kw):
        captured["call"] = lambda: orig_jac(*a, **kw)
        return orig_jac(*a, **kw)

    def cap_jnp(*a, **kw):
        captured["call"] = lambda: orig_jnp(*a, **kw)
        return orig_jnp(*a, **kw)

    P._prep_and_verify_pallas, P._prep_and_verify_jnp = cap_pallas, cap_jnp
    P._prep_and_verify_pallas_jac = cap_jac
    try:
        p256.verify_batch_prehashed(digests, sigs, pubs, pad_block=8192,
                                    scalar_prep="device")
    finally:
        P._prep_and_verify_pallas, P._prep_and_verify_jnp = (orig_pallas,
                                                             orig_jnp)
        P._prep_and_verify_pallas_jac = orig_jac
    if "call" in captured:
        jax.block_until_ready(captured["call"]())
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < seconds:
            jax.block_until_ready(captured["call"]())
            reps += 1
        krate = reps * 8192 / (time.perf_counter() - t0)
        _emit(f"verify_8k_kernel_{_platform()}", krate, "sigs/s", base_rate)

    # pipelined end-to-end: host packing of batch k+1 overlaps the device's
    # batch k (chain-sync batch-ingest profile; also hides the tunneled
    # chip's ~100 ms per-sync round trip).  TPU-only, and only when the
    # production dispatch unit (the fused pallas-jac program) is active;
    # a kernel failure skips the metric rather than voiding the config's
    # earlier lines (no _pallas_or_jnp safety net on this direct path).
    if _platform() == "tpu" and P.PALLAS_KERNEL == "jac":
        tile = P._pick_tile(8192)
        depth = 2

        def dispatch():
            inputs, *_meta = P._pack_device_inputs(digests, sigs, pubs, 8192)
            # w passed explicitly: the jitted default binds _WINDOW at
            # module load, NOT the PALLAS_JAC_WINDOW knob
            return P._prep_and_verify_pallas_jac(
                inputs, tile=tile, w=P.PALLAS_JAC_WINDOW)

        def check(res):
            res = np.asarray(res)
            assert bool(res[0].all()) and not bool(res[1].any())

        try:
            jax.block_until_ready(dispatch())  # warm
            from upow_tpu.benchutil import pipelined_loop

            reps, elapsed = pipelined_loop(dispatch, check, seconds, depth)
            _emit(f"verify_8k_pipelined_{_platform()}",
                  reps * 8192 / elapsed, "sigs/s", base_rate)
        except Exception:
            import traceback

            traceback.print_exc(file=sys.stderr)


def config4_replay(seconds: float):
    """Full-chain replay: mine a chain with sends, wipe the UTXO tables,
    rebuild from the tx log, check the fingerprint oracle."""
    from decimal import Decimal

    from upow_tpu.core import clock, curve, difficulty, point_to_string
    from upow_tpu.core.constants import SMALLEST
    from upow_tpu.core.header import BlockHeader
    from upow_tpu.core.merkle import merkle_root
    from upow_tpu.core.tx import Tx, TxInput, TxOutput
    from upow_tpu.mine.engine import MiningJob, mine
    from upow_tpu.state import ChainState
    from upow_tpu.verify import BlockManager
    from upow_tpu.wallet.builders import WalletBuilder

    difficulty.START_DIFFICULTY = Decimal("1.0")
    GENESIS_PREV = (18_884_643).to_bytes(32, "little").hex()

    async def scenario():
        state = ChainState()
        manager = BlockManager(state, sig_backend="host")
        builder = WalletBuilder(state)
        d, pub = curve.keygen(rng=0xC0DE)
        addr = point_to_string(pub)
        _, pub2 = curve.keygen(rng=0xC0DF)
        addr2 = point_to_string(pub2)
        n_blocks = 60
        for i in range(n_blocks):
            clock.advance(60)
            txs = []
            if i > 2 and i % 2:
                txs = [await builder.create_transaction(0xC0DE, addr2, "0.5")]
                for t in txs:
                    await state.add_pending_transaction(t)
                txs = await state.get_pending_transactions_limit(hex_only=False)
            diff, last = await manager.calculate_difficulty()
            prev = last["hash"] if last else GENESIS_PREV
            header = BlockHeader(
                previous_hash=prev, address=addr,
                merkle_root=merkle_root(txs), timestamp=clock.timestamp(),
                difficulty_x10=int(diff * 10), nonce=0)
            if last:
                r = mine(MiningJob(header.prefix_bytes(), prev, diff),
                         "python", batch=1 << 14, ttl=600)
                header.nonce = r.nonce
            assert await manager.create_block(header.hex(), txs, errors=[])
        want = await state.get_unspent_outputs_hash()
        t0 = time.perf_counter()
        await state.rebuild_utxos()
        dt = time.perf_counter() - t0
        assert await state.get_unspent_outputs_hash() == want
        state.close()
        return n_blocks / dt

    rate = asyncio.run(scenario())
    clock.reset()
    _emit("replay_validate", rate, "blocks/s", None)


def config5_sharded(seconds: float):
    """Mesh-sharded difficulty-8 search over every visible device."""
    import jax

    from upow_tpu.crypto import sha256 as sk
    from upow_tpu.parallel import make_mesh, pow_search_sharded

    job = _job("8.0")
    template = sk.make_template(job.prefix)
    spec = sk.target_spec(job.previous_hash, "8.0")
    mesh = make_mesh()
    n_dev = len(mesh.devices.ravel())
    # 2^28/device matches bench.py's production round size (raised from
    # 2^26 together with pipelining — TPU numbers from before that change
    # are not comparable under this metric name)
    per_dev = (1 << 28) if _platform() == "tpu" else (1 << 17)
    _ = int(pow_search_sharded(template, spec, 0, per_dev, mesh))
    # pipelined like the production mining loop (engine.mine, bench.py):
    # two rounds in flight hide the host<->device sync round trip
    from upow_tpu.benchutil import pipelined_loop

    base = [0]

    def dispatch():
        r = pow_search_sharded(template, spec, base[0], per_dev, mesh)
        base[0] = (base[0] + per_dev * n_dev) % (1 << 32)
        return r

    rounds, elapsed = pipelined_loop(dispatch, lambda r: int(r), seconds)
    rate = rounds * per_dev * n_dev / elapsed / 1e6
    base_rate = _python_loop_mhs(job.prefix)
    _emit(f"mine_d8_sharded_{n_dev}x_{_platform()}", rate, "MH/s", base_rate)


def _python_verify_baseline(seconds: float = 1.0) -> float:
    """Serial pure-python ECDSA verify rate — the baseline convention
    for the accept/intake/sync configs (the reference's dominant per-tx
    cost is one fastecdsa verify per input)."""
    from upow_tpu.core import curve

    dd, bpub = curve.keygen(rng=0xBA5E)
    sig = curve.sign(b"base", dd)
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        curve.verify(sig, b"base", bpub)
        n += 1
    return n / (time.perf_counter() - t0)


async def _chain_with_utxo_fanout(n_fan: int, n_per: int, rng_key: int):
    """Funded-chain scaffolding, now shared with the loadgen fixture —
    moved to upow_tpu.benchutil.chain_with_utxo_fanout."""
    from upow_tpu.benchutil import chain_with_utxo_fanout

    return await chain_with_utxo_fanout(n_fan, n_per, rng_key)


def _leaf_spends(parents, addr, d, pub):
    from upow_tpu.benchutil import leaf_spends

    return leaf_spends(parents, addr, d, pub)


def config6_block8k(seconds: float):
    """Full 8k-tx block accept, end to end through BlockManager: header +
    PoW checks, per-tx rules, ONE batched signature dispatch, batched
    UTXO double-spend set-diffs, and all state writes.  This is the
    README design point the reference never demonstrates (~8,300 tx per
    2 MB block, README.md:26-28; its accept path verifies signatures
    serially per input, transaction_input.py:100-109)."""
    from upow_tpu.core import curve

    async def scenario():
        # 255 x 32 = 8160 spendable leaf outputs
        state, manager, d, pub, addr, mids, mine_block = \
            await _chain_with_utxo_fanout(255, 32, 0xB10C)

        # block 4 (measured, cold): 8160 txs, each 1-in-1-out, signatures
        # never seen before — the worst-case accept
        def leaf_spends(parents):
            return _leaf_spends(parents, addr, d, pub)

        leaves = leaf_spends(mids)
        dt_cold = await mine_block(leaves)

        # block 5 (measured, warm): same shape, but every tx was verified
        # at "intake" first — the gossip profile, where the verdict cache
        # makes block accept pay zero signature work
        from upow_tpu.verify.txverify import TxVerifier, run_sig_checks

        verifier = TxVerifier(state)
        leaves2 = leaf_spends(leaves)
        for t in leaves2:
            c = await verifier.collect_sig_checks(t)
            if c is None:
                raise RuntimeError("warm-path tx failed to collect checks")
            # one call per tx, as real push_tx intake does — small batches
            # resolve to the host path, whose verdicts are the ones the
            # cache keeps (device verdicts are deliberately not cached)
            if not all(run_sig_checks(c, backend="auto")):
                raise RuntimeError("warm-path intake verification failed")
        dt_warm = await mine_block(leaves2)

        assert await state.get_next_block_id() == 6
        state.close()
        return len(leaves) / dt_cold, len(leaves2) / dt_warm

    # baseline: the reference's accept path verifies each input serially
    # (fastecdsa in C there; our measured pure-python loop here is the
    # same-host stand-in, consistent with the other configs)
    base_rate = _python_verify_baseline(seconds)

    rate_cold, rate_warm = asyncio.run(scenario())
    from upow_tpu.core import clock

    clock.reset()
    _emit(f"block_accept_8k_{_platform()}", rate_cold, "tx/s", base_rate)
    _emit(f"block_accept_8k_warm_{_platform()}", rate_warm, "tx/s", base_rate)


def config8_intake(seconds: float):
    """push_tx intake over real localhost HTTP: JSON parse + wire parse
    + rules + signature verify (native C++ on the host path) + pending
    insert + gossip spawn, one round trip per tx — the reference's
    per-tx gossip ingest cost (main.py:267-323)."""
    import tempfile

    from aiohttp.test_utils import TestClient, TestServer

    from upow_tpu.config import Config
    from upow_tpu.core import clock, curve
    from upow_tpu.node.app import Node

    N_TX = 2048  # fan a coinbase into this many spendable outputs

    async def scenario():
        # 10 x 224 = 2240 leaf outputs (<=255 per tx)
        state, manager, d, pub, addr, mids, _mine = \
            await _chain_with_utxo_fanout(10, 224, 0x17A4)
        txs = _leaf_spends(mids, addr, d, pub)
        assert len(txs) >= N_TX
        payloads = [t.hex() for t in txs[:N_TX]]

        cfg = Config()
        with tempfile.TemporaryDirectory() as tmp:
            cfg.node.db_path = ""
            cfg.node.seed_url = ""
            cfg.node.peers_file = f"{tmp}/nodes.json"
            cfg.node.ip_config_file = ""
            cfg.log.path = ""
            cfg.log.console = False
            node = Node(cfg, state=state)
            server = TestServer(node.app)
            await server.start_server()
            client = TestClient(server)
            node.started = True
            node.rate_limiter.enabled = False  # measuring us, not limits
            try:
                # warm one request (route setup, first-parse imports) —
                # outside the timed window AND the numerator
                r = await (await client.post(
                    "/push_tx", json={"tx_hex": payloads[0]})).json()
                assert r.get("ok"), r
                t0 = time.perf_counter()
                done = 0
                for p in payloads[1:]:
                    r = await (await client.post(
                        "/push_tx", json={"tx_hex": p})).json()
                    assert r.get("ok"), r
                    done += 1
                    if time.perf_counter() - t0 > seconds:
                        break
                elapsed = time.perf_counter() - t0
            finally:
                await client.close()
                await server.close()
                await node.close()
        return done / elapsed

    # baseline: serial pure-python verify, one per tx (the dominant
    # reference-side cost of intake)
    base_rate = _python_verify_baseline()

    rate = asyncio.run(scenario())
    clock.reset()
    _emit(f"push_tx_intake_{_platform()}", rate, "tx/s", base_rate)


def config10_coalesced_intake(seconds: float):
    """Concurrent push_tx through the coalescing mempool intake
    (upow_tpu/mempool/intake.py): waves of simultaneous HTTP requests
    share one signature dispatch per micro-batch instead of paying one
    per tx — the continuous-batching win over config 8's serial
    round-trips, measured on the same wire path."""
    import tempfile

    from aiohttp.test_utils import TestClient, TestServer

    from upow_tpu.config import Config
    from upow_tpu.core import clock
    from upow_tpu.node.app import Node

    N_TX = 2048
    WAVE = 64  # concurrent pushers per wave

    async def scenario():
        state, manager, d, pub, addr, mids, _mine = \
            await _chain_with_utxo_fanout(10, 224, 0xC0A1)
        txs = _leaf_spends(mids, addr, d, pub)
        assert len(txs) >= N_TX
        payloads = [t.hex() for t in txs[:N_TX]]

        cfg = Config()
        with tempfile.TemporaryDirectory() as tmp:
            cfg.node.db_path = ""
            cfg.node.seed_url = ""
            cfg.node.peers_file = f"{tmp}/nodes.json"
            cfg.node.ip_config_file = ""
            cfg.log.path = ""
            cfg.log.console = False
            node = Node(cfg, state=state)
            server = TestServer(node.app)
            await server.start_server()
            client = TestClient(server)
            node.started = True
            node.rate_limiter.enabled = False

            async def push(p):
                r = await (await client.post(
                    "/push_tx", json={"tx_hex": p})).json()
                assert r.get("ok"), r

            try:
                await push(payloads[0])  # warm, untimed
                t0 = time.perf_counter()
                done = 0
                for i in range(1, len(payloads), WAVE):
                    wave = payloads[i:i + WAVE]
                    await asyncio.gather(*[push(p) for p in wave])
                    done += len(wave)
                    if time.perf_counter() - t0 > seconds:
                        break
                elapsed = time.perf_counter() - t0
            finally:
                await client.close()
                await server.close()
                await node.close()
        return done / elapsed

    base_rate = _python_verify_baseline()

    rate = asyncio.run(scenario())
    clock.reset()
    _emit(f"push_tx_coalesced_{_platform()}", rate, "tx/s", base_rate)


def config11_perf_observatory(seconds: float):
    """The perf observatory: seeded wallet-population loadgen against
    the in-process node (Zipf reads, miner polling, push_tx bursts, ws
    churn) merged with kernel benches into one artifact
    (``observatory.json``) that the regression gate consumes.  Emits a
    suite-shaped line per endpoint so the driver's capture carries the
    SLO scoreboard too."""
    from upow_tpu.loadgen.observatory import (append_progress,
                                              run_observatory,
                                              write_artifact)
    from upow_tpu.loadgen.population import PopulationSpec

    spec = PopulationSpec(duration=min(seconds, 4.0))
    artifact = run_observatory(spec, bench_seconds=min(seconds / 4, 1.0))
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "observatory.json")
    write_artifact(artifact, out_path)
    progress = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "PROGRESS.jsonl")
    append_progress(artifact, progress)

    for ep, row in sorted(artifact["slo"]["endpoints"].items()):
        _emit(f"slo_{ep}_req_s", row["req_s"] or 0.0, "req/s", None)
        _emit(f"slo_{ep}_p95", row["p95_ms"], "ms", None)


def config12_verify_pipeline(seconds: float):
    """Pipelined block-verify engine vs the serial per-tx dispatch on
    the SAME host backend (ISSUE 7 acceptance): micro-batched
    submissions coalesced through the shared dispatch front with the
    verdict cache live (steady-state gossip profile) against one
    cache-bypassed ``verify_batch_native_cpu``-path call per tx.  The
    bench asserts byte-identical accept/reject verdicts between the two
    paths over >=1k mixed valid/invalid signatures before emitting."""
    from upow_tpu.benchutil import verify_pipeline_bench

    r = verify_pipeline_bench(seconds=min(seconds / 4, 1.0))
    assert r["verdicts_equal"], \
        "pipelined verdicts diverged from the serial path"
    _emit(f"verify_pipeline_{_platform()}", r["pipelined_tx_s"], "tx/s",
          r["serial_tx_s"])
    _emit(f"verify_pipeline_serial_{_platform()}", r["serial_tx_s"],
          "tx/s", None)


def config13_readpath_cache(seconds: float):
    """Block-anchored hot-state read cache (ISSUE 9 acceptance):
    Zipfian wallet readers + miner polling against the in-process node,
    the SAME deterministic schedule replayed bypassed and cached while
    blocks land at a fixed cadence (every window re-pays invalidation).
    The scenario's built-in differential — cached vs recomputed bodies
    byte-identical at every stage, including across a forced
    ``remove_blocks`` reorg — must hold or the run refuses to emit."""
    import asyncio

    from upow_tpu.loadgen.readpath import ReadpathSpec, run_readpath

    r = asyncio.run(run_readpath(ReadpathSpec()))
    assert r["differential"]["ok"], \
        "readpath differential diverged: cached body != recomputed body"
    _emit("readpath_bypass_p99", r["bypass"]["p99_ms"], "ms", None,
          direction="lower")
    _emit("readpath_cached_p99", r["cached"]["p99_ms"], "ms", None,
          direction="lower")
    _emit("readpath_speedup_p99", r["speedup_p99"], "x", None,
          direction="higher")
    _emit("readpath_hit_ratio", r["cached_pass"]["hit_ratio"], "ratio",
          None, direction="higher")


def config14_coresidency(seconds: float):
    """Co-residency on the device runtime (ISSUE 10 acceptance):
    saturating miner stream + block-verify + mempool-intake sig batches
    on ONE DeviceRuntime, with the built-in differential — every
    concurrent verdict byte-identical to the serial host reference AND
    a serial one-dispatch-per-batch pass — required before any number
    is emitted.  Headlines: cross-source coalescing ratio (fewer
    dispatches), shared-dispatch occupancy, and the block-verify queue
    wait under the flood (bounded wait = no starvation)."""
    from upow_tpu import telemetry
    from upow_tpu.loadgen.coresidency import (CoresidencySpec,
                                              run_coresidency)

    telemetry.configure()
    r = run_coresidency(
        CoresidencySpec() if seconds >= 4 else CoresidencySpec.smoke())
    assert r["differential"]["ok"], \
        "coresidency differential diverged from the serial paths"
    _emit("coresidency_coalesce_ratio", r["coalesce_ratio"], "x", None,
          direction="higher")
    _emit("coresidency_dispatch_reduction", r["dispatch_reduction"], "x",
          None, direction="higher")
    _emit("coresidency_occupancy", r["concurrent"]["occupancy"] or 0.0,
          "ratio", None, direction="higher")
    _emit("coresidency_verify_wait_p99",
          r["concurrent"]["verify_wait_p99_ms"], "ms", None,
          direction="lower")


def config15_accept_resident(seconds: float):
    """HBM-resident UTXO accept path (ISSUE 11 acceptance): end-to-end
    8k-tx block accept through the host-round-trip path (per-table SQL
    membership scans) vs the fused resident path (device membership
    probe + digest prep in ONE runtime dispatch, shadow map consulted
    only on fingerprint ambiguity).  The byte-identity differential —
    resident probe vs host shadow map vs SQL, plus the unspent-set
    fingerprint across a FORCED REORG and re-accept — must hold or the
    run refuses to emit (the helper zeroes the speedups too)."""
    from upow_tpu.benchutil import accept_resident_bench

    r = accept_resident_bench(seconds=min(seconds / 4, 1.0))
    assert r["differential_ok"], \
        "resident accept differential diverged from the SQL path"
    _emit(f"accept_resident_8k_{_platform()}", r["resident_tx_s"], "tx/s",
          r["serial_tx_s"])
    _emit(f"accept_serial_8k_{_platform()}", r["serial_tx_s"], "tx/s",
          None)
    _emit(f"accept_scan_speedup_{_platform()}", r["scan_speedup"], "x",
          None, direction="higher")
    _emit("accept_shadow_consults", float(r["shadow_consults"]), "",
          None, direction="lower")


def config16_mining_mesh(seconds: float):
    """Resident mesh-sharded nonce search (ISSUE 12 acceptance): one
    compiled SPMD program across the dp mesh, every job field a traced
    argument (a chain-tip change never recompiles), dispatched through
    the device runtime under source "mine".  The bit-identity
    differential — mesh min-hit == serial jnp min-hit per window over
    >= 3 seeded jobs, plus disjoint shard coverage from the engine's
    own accounting — must hold or the sharded headline and the speedup
    are zeroed (the gate trips on correctness, not just slowdowns)."""
    from upow_tpu.benchutil import mining_mesh_bench

    batch = (1 << 22) if _platform() == "tpu" else (1 << 14)
    r = mining_mesh_bench(seconds=min(seconds / 2, 4.0),
                          batch_per_device=batch,
                          shard_counts=(1, 2, 4, 8))
    assert r["differential_ok"], \
        "mesh search diverged from the serial path"
    _emit(f"mine_mesh_sharded_{r['n_devices']}x_{_platform()}",
          r["sharded_mhs"], "MH/s", r["serial_mhs"], direction="higher")
    _emit(f"mine_mesh_serial_{_platform()}", r["serial_mhs"], "MH/s",
          None, direction="higher")
    _emit(f"mine_mesh_speedup_{_platform()}", r["speedup"], "x", None,
          direction="higher")
    for row in r["per_shard_counts"]:
        _emit(f"mine_mesh_{row['shards']}shard_{_platform()}",
              row["mhs"], "MH/s", None, direction="higher")


def config9_sync(seconds: float):
    """End-to-end chain sync over real localhost HTTP: node B downloads
    node A's chain in pages (prefetch pipeline, page-level signature
    dispatch, batched txid seeding per device config) and accepts every
    block — the full reference catch-up path (main.py:97-150) measured
    as wire-to-state throughput."""
    import tempfile

    from aiohttp.test_utils import TestServer

    from upow_tpu.config import Config
    from upow_tpu.core import clock
    from upow_tpu.node.app import Node
    from upow_tpu.state import ChainState

    N_BLOCKS = 240  # after the 3 fan-out blocks; 2 spends per block

    async def scenario():
        state, manager, d, pub, addr, mids, mine_block = \
            await _chain_with_utxo_fanout(10, 64, 0x57AC)
        leaves = _leaf_spends(mids, addr, d, pub)
        assert len(leaves) >= 2 * N_BLOCKS
        it = iter(leaves)
        for _ in range(N_BLOCKS):
            await mine_block([next(it), next(it)])
        total_blocks = 3 + N_BLOCKS
        # block 1 is coinbase-only; then the fan (1 tx), the mids (10),
        # and 2 spends per measured block — plus one coinbase each
        total_txs = sum(1 + n for n in ([0, 1, 10] + [2] * N_BLOCKS))

        def node_cfg(tmp, name):
            cfg = Config()
            cfg.node.db_path = ""
            cfg.node.seed_url = ""
            cfg.node.peers_file = f"{tmp}/{name}-nodes.json"
            cfg.node.ip_config_file = ""
            cfg.node.sync_fetch_interval = 0.0
            cfg.node.sync_page = 64  # several pages: prefetch pipeline on
            cfg.log.path = ""
            cfg.log.console = False
            return cfg

        with tempfile.TemporaryDirectory() as tmp:
            node_a = Node(node_cfg(tmp, "a"), state=state)
            server_a = TestServer(node_a.app)
            await server_a.start_server()
            node_a.started = True
            node_a.rate_limiter.enabled = False
            # node B needs no HTTP server: it syncs as a CLIENT of A
            node_b = Node(node_cfg(tmp, "b"), state=ChainState())
            node_b.started = True
            try:
                t0 = time.perf_counter()
                ok = await node_b.sync_blockchain(
                    f"http://127.0.0.1:{server_a.port}")
                elapsed = time.perf_counter() - t0
                assert ok is True, ok
                assert (await node_b.state.get_next_block_id()
                        == total_blocks + 1)
                assert (await node_a.state.get_unspent_outputs_hash()
                        == await node_b.state.get_unspent_outputs_hash())
            finally:
                await server_a.close()
                await node_a.close()
                await node_b.close()
        return total_blocks / elapsed, total_txs / elapsed

    # baseline convention (config 6): serial pure-python verify — the
    # reference's dominant per-tx catch-up cost
    base_rate = _python_verify_baseline()

    blocks_s, txs_s = asyncio.run(scenario())
    clock.reset()
    _emit(f"sync_http_blocks_{_platform()}", blocks_s, "blocks/s", None)
    _emit(f"sync_http_txs_{_platform()}", txs_s, "tx/s", base_rate)


def config7_txid_batch(seconds: float):
    """Host hashlib vs device sha256_batch_jnp for an 8k-tx page of
    ~400 B payloads — the measured crossover behind device.txid_backend
    (crypto/sha256.txid_batch; reference manager.py:365-378)."""
    import random

    from upow_tpu.crypto.sha256 import sha256_batch_jnp

    rng = random.Random(0xD1E5)
    payloads = [rng.randbytes(rng.randint(150, 600)) for _ in range(8192)]

    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        for p in payloads:
            hashlib.sha256(p).digest()
        n += len(payloads)
    host_rate = n / (time.perf_counter() - t0)
    _emit(f"txid_batch_host_{_platform()}", host_rate, "hash/s", None)

    sha256_batch_jnp(payloads)  # compile warmup
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        sha256_batch_jnp(payloads)
        n += len(payloads)
    dev_rate = n / (time.perf_counter() - t0)
    _emit(f"txid_batch_device_{_platform()}", dev_rate, "hash/s", host_rate)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5,6")
    ap.add_argument("--seconds", type=float, default=8.0)
    ap.add_argument("--require-tpu", action="store_true",
                    help="exit 3 unless the real chip answers the probe "
                         "(tpu_watch queue gating)")
    args = ap.parse_args()
    if args.require_tpu and _platform() in ("cpu", "hung"):
        print(json.dumps({"error": f"--require-tpu: platform={_platform()}"}),
              flush=True)
        return 3

    from upow_tpu import compile_cache

    compile_cache.enable(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    runners = {
        "1": lambda: config1_cpu_reference(args.seconds),
        "2": lambda: config2_fractional(
            args.seconds, "pallas" if _platform() == "tpu" else "jnp"),
        "3": lambda: config3_batch_verify(args.seconds),
        "4": lambda: config4_replay(args.seconds),
        "5": lambda: config5_sharded(args.seconds),
        "6": lambda: config6_block8k(args.seconds),
        "7": lambda: config7_txid_batch(args.seconds),
        "8": lambda: config8_intake(args.seconds),
        "9": lambda: config9_sync(args.seconds),
        "10": lambda: config10_coalesced_intake(args.seconds),
        "11": lambda: config11_perf_observatory(args.seconds),
        "12": lambda: config12_verify_pipeline(args.seconds),
        "13": lambda: config13_readpath_cache(args.seconds),
        "14": lambda: config14_coresidency(args.seconds),
        "15": lambda: config15_accept_resident(args.seconds),
        "16": lambda: config16_mining_mesh(args.seconds),
    }
    needs_device = {"2", "3", "5", "7", "16"}
    failed = []
    for key in args.configs.split(","):
        key = key.strip()
        if key in needs_device and _platform() == "hung":
            print(json.dumps({
                "metric": f"config{key}_error", "value": 0.0, "unit": "",
                "vs_baseline": 0.0, "error": "jax backend hung"}), flush=True)
            failed.append(key)
            continue
        try:
            runners[key]()
        except Exception as e:  # keep the suite going; record the failure
            print(json.dumps({
                "metric": f"config{key}_error", "value": 0.0, "unit": "",
                "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
            failed.append(key)
    # under --require-tpu a config that produced no numbers must fail the
    # run, or tpu_watch would mark the queue step done with nothing
    # measured (rc semantics mirror tpu_ab's all-cells-or-nonzero)
    return 3 if (args.require_tpu and failed) else 0


if __name__ == "__main__":
    raise SystemExit(main())
