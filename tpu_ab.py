"""Chip A/B matrix for the verify kernel: window bits x tile cap.

VERDICT r4 weak #2: the w=5 window and the tile sweep have been "armed"
for two rounds with no measured rates.  This harness spends them the
moment the chip is healthy (tpu_watch.py runs it in the queue).

Every cell runs in a FRESH subprocess — the knobs (UPOW_JAC_WINDOW,
UPOW_TILE_CAP) are read at import, and one wedged PJRT client must not
poison the rest of the matrix.  Results aggregate to TPU_AB_r05.json.

    python tpu_ab.py             # run the matrix (subprocess per cell)
    python tpu_ab.py --one       # single measurement in THIS process
                                 # (knobs from env), prints one JSON line
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "TPU_AB_r05.json")

# (window, tile_cap) cells.  w=4/t=1024 is the production default —
# measured first so the matrix always has its baseline even if the
# tunnel dies mid-sweep.
_MATRIX = [(4, 1024), (5, 1024), (4, 512), (5, 512), (4, 256), (5, 256)]


def _measure_one(seconds: float, lanes: int) -> dict:
    from upow_tpu import compile_cache
    compile_cache.enable(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    import jax
    import numpy as np

    from upow_tpu.benchutil import (probe_platform, timed_reps,
                                    verify_fixture)
    from upow_tpu.crypto import p256 as P

    platform = probe_platform(120.0)
    if platform in (None, "cpu"):
        return {"error": f"no tpu (platform={platform})"}

    w = P.PALLAS_JAC_WINDOW
    digests, sigs, pubs, _ = verify_fixture(lanes)
    tile = P._pick_tile(lanes)
    inputs, *_ = P._pack_device_inputs(digests, sigs, pubs, lanes)

    def kernel_call():
        return P._prep_and_verify_pallas_jac(inputs, tile=tile, w=w)

    t0 = time.perf_counter()
    res = np.asarray(jax.block_until_ready(kernel_call()))
    compile_s = time.perf_counter() - t0
    if not (bool(res[0].all()) and not bool(res[1].any())):
        return {"error": "kernel verdicts wrong", "w": w, "tile": tile}
    reps, elapsed = timed_reps(
        lambda: jax.block_until_ready(kernel_call()), seconds)
    return {
        "platform": platform, "w": w, "tile": tile, "lanes": lanes,
        "kernel_sigs_per_s": round(reps * lanes / elapsed, 1),
        "reps": reps, "compile_s": round(compile_s, 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", action="store_true")
    ap.add_argument("--seconds", type=float, default=6.0)
    ap.add_argument("--lanes", type=int, default=8192)
    ap.add_argument("--cell-timeout", type=float, default=420.0)
    args = ap.parse_args()

    if args.one:
        print(json.dumps(_measure_one(args.seconds, args.lanes)), flush=True)
        return 0

    # resume: cells already measured in a previous (partially wedged)
    # run are kept, so a retry only burns chip time on what's missing —
    # but only if that run used the same lanes/seconds (comparability)
    done = {}
    try:
        with open(_OUT) as f:
            prev = json.load(f)
        if prev.get("params") == {"lanes": args.lanes,
                                  "seconds": args.seconds}:
            for c in prev.get("cells", []):
                if "kernel_sigs_per_s" in c:
                    done[(c["w"], c["tile_cap"])] = c
    except (OSError, ValueError):
        pass

    cells = []
    for w, cap in _MATRIX:
        if (w, cap) in done:
            cells.append(done[(w, cap)])
            continue
        env = dict(os.environ)
        env["UPOW_JAC_WINDOW"] = str(w)
        env["UPOW_TILE_CAP"] = str(cap)
        cmd = [sys.executable, os.path.abspath(__file__), "--one",
               "--seconds", str(args.seconds), "--lanes", str(args.lanes)]
        t0 = time.time()
        # Popen + killpg, not subprocess.run: a wedged PJRT client must be
        # killed as a whole GROUP or orphans keep the pipe (and the
        # tunnel) open past the timeout — the repo's one-client rule
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, start_new_session=True)
        try:
            out, err = proc.communicate(timeout=args.cell_timeout)
            line = out.strip().splitlines()
            cell = json.loads(line[-1]) if line else {
                "error": f"no output rc={proc.returncode}",
                "stderr": err[-400:]}
        except subprocess.TimeoutExpired:
            import signal
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.communicate()
            cell = {"error": "cell timeout (tunnel wedged?)"}
        except ValueError:
            cell = {"error": "unparseable output"}
        cell.setdefault("w", w)
        cell["tile_cap"] = cap
        cell["wall_s"] = round(time.time() - t0, 1)
        cells.append(cell)
        print(json.dumps(cell), flush=True)
        if "timeout" in str(cell.get("error", "")):
            break  # a wedged tunnel will eat every remaining cell

    ok = [c for c in cells if "kernel_sigs_per_s" in c]
    summary = {"params": {"lanes": args.lanes, "seconds": args.seconds},
               "cells": cells}
    if ok:
        best = max(ok, key=lambda c: c["kernel_sigs_per_s"])
        base = next((c for c in ok if c["w"] == 4 and c["tile_cap"] == 1024),
                    None)
        summary["best"] = {k: best[k] for k in
                          ("w", "tile", "kernel_sigs_per_s")}
        if base:
            summary["best_vs_default"] = round(
                best["kernel_sigs_per_s"] / base["kernel_sigs_per_s"], 3)
    with open(_OUT, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"wrote": _OUT, "ok_cells": len(ok)}), flush=True)
    # rc 0 only when EVERY cell measured — a partial matrix must look
    # failed to tpu_watch so it retries (resume skips the done cells)
    return 0 if len(ok) == len(_MATRIX) else 1


if __name__ == "__main__":
    raise SystemExit(main())
